"""graftburst acceptance (ISSUE 17): WAL group-commit, multi-client
co-batching, negotiated binary framing + pipelining, and the capped
``retry_after`` discipline.

The contract, pinned deterministically:

* GROUP-COMMIT PARITY: a run with one fsync barrier per scheduler
  round produces bitwise the suggestion stream of the per-tell-fsync
  run, at a fraction of the fsyncs; a machine crash in the
  flush-to-barrier window loses ONLY the unbarriered suffix (replay
  restores exactly the barriered prefix, zero duplicates);
* CO-BATCHING PARITY: N concurrent ``fmin(engine=True)`` clients of
  one study family share ONE service (the registry), and each client's
  loss stream is bitwise its solo sequential run;
* PROTOCOL NEGOTIATION: binary client vs JSON server (and vice versa)
  falls back cleanly; a malformed frame is a typed error reply, never
  a hang; pipelined replies land on the right futures under
  reordering;
* BACKOFF CAPS: every retry loop sleeps ``min(server hint,
  RETRY_AFTER_CAP)``, never the raw hint.
"""

import io
import json
import os
import socket
import socketserver
import threading
import time

import numpy as np
import pytest

from hyperopt_tpu import base, hp, tpe_jax
from hyperopt_tpu.base import Trials
from hyperopt_tpu.exceptions import Overloaded
from hyperopt_tpu.serve import SuggestService
from hyperopt_tpu.serve.frames import (
    MAX_FRAME,
    FrameConn,
    FrameError,
    pack,
    read_frame,
    unpack,
    write_frame,
)
from hyperopt_tpu.serve.service import RETRY_AFTER_CAP, serve_forever
from hyperopt_tpu.utils.wal import TellWAL


@pytest.fixture(autouse=True)
def _lockdep_armed(monkeypatch):
    from hyperopt_tpu.analysis import lockdep

    dep = lockdep.arm_scheduler_class(monkeypatch)
    yield dep
    assert dep.inversions == 0, dep.errors


SPACE = {
    "x": hp.uniform("x", -5, 5),
    "c": hp.choice("c", [0, 1, 2]),
}
ALGO_KW = dict(n_cand=8, n_cand_cat=4)


def loss_fn(vals):
    return (vals["x"] - 1) ** 2 / 10 + 0.1 * vals["c"]


def _spawn(server):
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return t


# ---------------------------------------------------------------------------
# WAL group-commit: barrier semantics + torn-window recovery
# ---------------------------------------------------------------------------


def test_wal_barrier_amortizes_fsyncs(tmp_path):
    wal = TellWAL(str(tmp_path / "w.wal"))
    for i in range(8):
        wal.append("tell", {"tid": i, "state": 2}, sync=False)
    before = wal.fsyncs
    assert wal.barrier() is True
    assert wal.fsyncs == before + 1  # ONE fsync covers all 8 records
    assert wal.barrier() is False  # nothing unbarriered: a no-op
    assert wal.fsyncs == before + 1
    wal.close()
    fresh = TellWAL(str(tmp_path / "w.wal"))
    assert [r["tid"] for r in fresh.replay()] == list(range(8))
    assert fresh.total_tells == 8
    fresh.close()


def test_wal_sync_append_clears_barrier_debt(tmp_path):
    wal = TellWAL(str(tmp_path / "w.wal"))
    wal.append("tell", {"tid": 0, "state": 2}, sync=False)
    wal.append("tell", {"tid": 1, "state": 2}, sync=True)
    # the sync append's fsync covered the flushed predecessor too
    assert wal.barrier() is False
    wal.close()


def test_machine_crash_in_window_keeps_barriered_prefix(tmp_path):
    """The widened torn-tail rule: a machine crash between a round's
    flushes and its barrier can drop the WHOLE unbarriered suffix --
    replay restores exactly the barriered prefix and appends continue
    from it, zero duplicates."""
    path = str(tmp_path / "w.wal")
    wal = TellWAL(path)
    for i in range(4):
        wal.append("tell", {"tid": i, "state": 2}, sync=False)
    wal.barrier()
    barriered = os.path.getsize(path)
    for i in range(4, 7):
        wal.append("tell", {"tid": i, "state": 2}, sync=False)
    wal.close()
    # simulate the lost unsynced suffix: everything past the barrier
    # is gone, plus a torn half-record straddling the cut
    with open(path, "r+b") as f:
        f.truncate(barriered + 7)
    fresh = TellWAL(path)
    assert [r["tid"] for r in fresh.replay()] == [0, 1, 2, 3]
    assert fresh.total_tells == 4
    assert fresh.append("tell", {"tid": 4, "state": 2}) == 4
    fresh.close()


def _run_rounds(root, group_commit, rounds=6, width=4):
    svc = SuggestService(
        SPACE, root=root, max_batch=8, background=False,
        n_startup_jobs=2, snapshot_cadence=1000, study_queue_cap=8,
        group_commit=group_commit, **ALGO_KW,
    )
    names = ["a", "b", "c", "d"]
    handles = {n: svc.create_study(n, seed=i) for i, n in enumerate(names)}
    streams = {n: [] for n in names}
    for _ in range(rounds):
        # `width` asks in flight per study: the burst shape whose
        # tells all land inside ONE barrier window
        futs = {n: [handles[n].ask_async() for _ in range(width)]
                for n in names}
        while not all(f.done() for fs in futs.values() for f in fs):
            svc.pump()
        for n, fs in futs.items():
            for f in fs:
                tid, vals = f.result(timeout=30)
                streams[n].append((tid, json.dumps(vals, sort_keys=True)))
                handles[n].tell(tid, loss_fn(vals))
    counters = dict(svc.counters)
    svc.shutdown()
    return streams, counters


def test_group_commit_bitwise_parity_and_fsync_amortization(tmp_path):
    gc_streams, gc = _run_rounds(str(tmp_path / "gc"), True)
    pt_streams, pt = _run_rounds(str(tmp_path / "pt"), False)
    assert gc_streams == pt_streams  # fsync timing is stream-invisible
    assert gc["wal_tells"] == pt["wal_tells"] == 96
    assert pt["wal_fsyncs"] >= pt["wal_tells"]  # per-tell: one each
    assert gc["group_commit_barriers"] > 0
    assert pt["group_commit_barriers"] == 0
    # one barrier per WAL per round (plus the per-study header/guard
    # publishes), NOT one fsync per tell
    assert gc["wal_fsyncs"] < 0.4 * pt["wal_fsyncs"]
    assert gc["wal_fsyncs"] / gc["wal_tells"] < 0.4


def test_group_commit_crash_window_zero_lost_zero_duplicate(tmp_path):
    """Kill in the new flush-to-barrier crash window: the acked
    (flushed) tell survives a process crash, restore sees it exactly
    once, and a client re-tell dedups."""
    from hyperopt_tpu.distributed.faults import FaultPlan, SimulatedCrash

    root = str(tmp_path / "gc")
    plan = FaultPlan(seed=0).arm(
        "serve_group_commit_after_flush_before_barrier", at=1
    )
    svc = SuggestService(
        SPACE, root=root, fs=plan.fs(), max_batch=8, background=False,
        n_startup_jobs=2, snapshot_cadence=1000, **ALGO_KW,
    )
    h = svc.create_study("a", seed=5)
    fut = h.ask_async()
    svc.pump()
    tid, vals = fut.result(timeout=30)
    h.tell(tid, loss_fn(vals))  # flushed, barrier still pending
    with pytest.raises(SimulatedCrash):
        h.ask_async()
        svc.pump()  # the next round's barrier hits the armed point
    assert plan.stats[
        "crash:serve_group_commit_after_flush_before_barrier"
    ] == 1
    svc2 = SuggestService(
        SPACE, root=root, fs=FaultPlan(seed=1).fs(), max_batch=8,
        background=False, n_startup_jobs=2, snapshot_cadence=1000,
        **ALGO_KW,
    )
    h2 = svc2.create_study("a", seed=5)
    st = svc2.scheduler.study("a")
    assert st.buf.count == 1  # the flushed tell survived the crash
    assert st.persist.wal.total_tells == 1
    h2.tell(tid, loss_fn(vals), vals=vals)  # lost-ack client re-tell
    assert st.persist.wal.total_tells == 1  # absorbed exactly once
    svc2.shutdown()


# ---------------------------------------------------------------------------
# frames: codec + framing discipline
# ---------------------------------------------------------------------------


def test_codec_roundtrip():
    obj = {
        "op": "ask", "study": "fmin-2", "rid": 7, "f": -2.5,
        "flags": [True, False, None], "nested": {"k": [1, {"d": 2}]},
        "blob": b"\x00\xffbytes", "big": 2**40,
    }
    assert unpack(pack(obj)) == obj


def test_codec_rejects_non_protocol_values():
    with pytest.raises(TypeError):
        pack({"bad": object()})


def test_codec_typed_errors():
    with pytest.raises(FrameError):
        unpack(b"")  # tag past end
    with pytest.raises(FrameError):
        unpack(b"\x99")  # unknown tag
    with pytest.raises(FrameError):
        unpack(pack("x") + b"junk")  # trailing bytes
    with pytest.raises(FrameError):
        unpack(pack({"a": 1})[:-2])  # truncated payload


def test_read_frame_discipline():
    assert read_frame(io.BytesIO(b"")) is None  # clean EOF
    with pytest.raises(FrameError):
        read_frame(io.BytesIO(b"\x00\x00\x00\x00"))  # zero length
    with pytest.raises(FrameError):
        read_frame(io.BytesIO(
            (MAX_FRAME + 1).to_bytes(4, "big")
        ))  # hostile length prefix must not allocate
    with pytest.raises(FrameError):
        read_frame(io.BytesIO(b"\x00\x00\x00\x08" + b"ab"))  # short body
    buf = io.BytesIO()
    write_frame(buf, {"ok": True})
    buf.seek(0)
    assert read_frame(buf) == {"ok": True}


# ---------------------------------------------------------------------------
# negotiation + pipelining over real sockets
# ---------------------------------------------------------------------------


def _tcp_service(**kw):
    svc = SuggestService(
        SPACE, background=True, max_batch=8, n_startup_jobs=2,
        **ALGO_KW, **kw,
    )
    srv = serve_forever(svc, port=0)
    _spawn(srv)
    return svc, srv


def _teardown(svc, srv):
    srv.shutdown()
    srv.server_close()
    svc.shutdown()


def test_binary_pipelining_end_to_end():
    svc, srv = _tcp_service()
    sock = socket.create_connection(srv.server_address[:2], timeout=30)
    conn = FrameConn(sock.makefile("rwb"))
    try:
        assert conn.binary is True  # negotiated up
        # four requests in flight before the first reply is read
        futs = [
            conn.submit({"op": "ping"}),
            conn.submit({"op": "create_study", "name": "s", "seed": 3}),
            conn.submit({"op": "ask", "study": "s", "timeout": 30}),
            conn.submit({"op": "studies"}),
        ]
        ping, created, ask, studies = [conn.drain(f) for f in futs]
        assert ping["pong"] is True
        assert created["ok"], created
        assert ask["ok"], ask
        assert studies["studies"] == ["s"]
        told = conn.call({
            "op": "tell", "study": "s", "tid": ask["tid"], "loss": 0.5,
        })
        assert told["ok"], told
    finally:
        conn.close()
        sock.close()
        _teardown(svc, srv)


def test_json_client_against_binary_server():
    """An old client never says hello: the connection stays JSON-lines
    end to end (the server-side fallback)."""
    svc, srv = _tcp_service()
    sock = socket.create_connection(srv.server_address[:2], timeout=30)
    f = sock.makefile("rwb")

    def rpc(**req):
        f.write((json.dumps(req) + "\n").encode())
        f.flush()
        return json.loads(f.readline())

    try:
        assert rpc(op="ping")["pong"] is True
        assert rpc(op="create_study", name="s", seed=3)["ok"]
        r = rpc(op="ask", study="s", timeout=30)
        assert r["ok"], r
        assert rpc(op="tell", study="s", tid=r["tid"], loss=0.5)["ok"]
    finally:
        f.close()
        sock.close()
        _teardown(svc, srv)


class _OldJsonServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _OldJsonHandler(socketserver.StreamRequestHandler):
    """A pre-graftburst peer: JSON lines, strictly in order, no rid
    echo, and ``hello`` is an unknown op."""

    def handle(self):
        for raw in self.rfile:
            req = json.loads(raw)
            if req.get("op") == "hello":
                reply = {"ok": False, "error": "unknown op 'hello'"}
            else:
                reply = {"ok": True, "echo": req.get("n")}
            self.wfile.write((json.dumps(reply) + "\n").encode())
            self.wfile.flush()


def test_binary_client_against_json_server_falls_back():
    srv = _OldJsonServer(("127.0.0.1", 0), _OldJsonHandler)
    _spawn(srv)
    sock = socket.create_connection(srv.server_address[:2], timeout=30)
    conn = FrameConn(sock.makefile("rwb"))
    try:
        assert conn.binary is False  # the old server declined hello
        futs = [conn.submit({"op": "x", "n": i}) for i in range(3)]
        for i, fut in enumerate(futs):
            # rid-less in-order replies resolve FIFO onto the right
            # futures
            assert conn.drain(fut)["echo"] == i
    finally:
        conn.close()
        sock.close()
        srv.shutdown()
        srv.server_close()


class _ReorderHandler(socketserver.StreamRequestHandler):
    """A binary server that answers two pipelined requests in REVERSE
    order: only rid correlation can land them correctly."""

    def handle(self):
        self.rfile.readline()  # the hello line
        self.wfile.write(
            (json.dumps({"ok": True, "proto": 2}) + "\n").encode()
        )
        self.wfile.flush()
        reqs = [read_frame(self.rfile), read_frame(self.rfile)]
        for req in reversed(reqs):
            write_frame(self.wfile, {
                "ok": True, "echo": req["n"], "rid": req["rid"],
            })
        self.wfile.flush()


def test_pipelined_replies_reordered_land_on_correct_futures():
    srv = _OldJsonServer(("127.0.0.1", 0), _ReorderHandler)
    _spawn(srv)
    sock = socket.create_connection(srv.server_address[:2], timeout=30)
    conn = FrameConn(sock.makefile("rwb"))
    try:
        assert conn.binary is True
        f0 = conn.submit({"op": "x", "n": 0})
        f1 = conn.submit({"op": "x", "n": 1})
        assert conn.drain(f0)["echo"] == 0  # reply for f1 arrives first
        assert f1.result(timeout=0)["echo"] == 1
    finally:
        conn.close()
        sock.close()
        srv.shutdown()
        srv.server_close()


def test_malformed_frame_is_typed_error_not_hang():
    svc, srv = _tcp_service()
    sock = socket.create_connection(srv.server_address[:2], timeout=30)
    conn = FrameConn(sock.makefile("rwb"))
    try:
        assert conn.binary is True
        conn.f.write(b"\x00\x00\x00\x00")  # a zero-length "frame"
        conn.f.flush()
        sock.shutdown(socket.SHUT_WR)
        reply = read_frame(conn.f)
        assert reply["ok"] is False
        assert reply["error_type"] == "FrameError"
        assert read_frame(conn.f) is None  # server hung up cleanly
    finally:
        conn.close()
        sock.close()
        _teardown(svc, srv)


def test_truncated_frame_is_typed_error_not_hang():
    svc, srv = _tcp_service()
    sock = socket.create_connection(srv.server_address[:2], timeout=30)
    conn = FrameConn(sock.makefile("rwb"))
    try:
        assert conn.binary is True
        conn.f.write(b"\x00\x00\x00\x64" + b"short")  # 100 declared, 5 sent
        conn.f.flush()
        sock.shutdown(socket.SHUT_WR)  # EOF mid-frame on the server
        reply = read_frame(conn.f)
        assert reply["ok"] is False
        assert reply["error_type"] == "FrameError"
    finally:
        conn.close()
        sock.close()
        _teardown(svc, srv)


def test_ask_batch_over_tcp_coalesces():
    svc, srv = _tcp_service()
    sock = socket.create_connection(srv.server_address[:2], timeout=30)
    conn = FrameConn(sock.makefile("rwb"))
    names = ["a", "b", "c"]
    try:
        for i, n in enumerate(names):
            assert conn.call(
                {"op": "create_study", "name": n, "seed": 10 + i}
            )["ok"]
        reply = conn.call({
            "op": "ask_batch", "names": names, "timeout": 30,
        })
        assert reply["ok"], reply
        for n in names:
            r = reply["results"][n]
            assert r["ok"], (n, r)
            assert conn.call({
                "op": "tell", "study": n, "tid": r["tid"], "loss": 0.5,
            })["ok"]
        missing = conn.call({
            "op": "ask_batch", "names": ["nope"], "timeout": 5,
        })
        assert missing["results"]["nope"]["error_type"] == "UnknownStudy"
    finally:
        conn.close()
        sock.close()
        _teardown(svc, srv)


# ---------------------------------------------------------------------------
# the capped retry_after discipline (satellite 6)
# ---------------------------------------------------------------------------


def _connect_client(svc, **kw):
    from hyperopt_tpu.client import connect

    domain = base.Domain(loss_fn, SPACE)
    return connect(
        svc, tpe_jax.suggest, domain, Trials(),
        np.random.default_rng(0), fn=loss_fn, **kw,
    )


def test_submit_one_backoff_sleeps_capped(monkeypatch):
    svc = SuggestService(
        SPACE, max_batch=8, background=False, n_startup_jobs=2,
        **ALGO_KW,
    )
    client, _, _, _ = _connect_client(svc, ask_ahead=1, max_submits=5)
    refusals = iter([99.0, 42.0])
    orig = svc._submit

    def flaky(study, timeout=None, replay=None):
        try:
            ra = next(refusals)
        except StopIteration:
            return orig(study, timeout=timeout, replay=replay)
        raise Overloaded("busy", retry_after=ra, reason="queue_full")

    monkeypatch.setattr(svc, "_submit", flaky)
    sleeps = []
    monkeypatch.setattr(time, "sleep", lambda s: sleeps.append(s))
    client._submit_one(time.perf_counter() + 60.0)
    # the wild 99s hint and the 42s hint both sleep the CAP, not the
    # raw server value
    assert sleeps == [RETRY_AFTER_CAP, RETRY_AFTER_CAP]
    svc.shutdown()


def test_handle_ask_backoff_sleeps_capped(monkeypatch):
    svc = SuggestService(
        SPACE, max_batch=8, background=False, n_startup_jobs=2,
        **ALGO_KW,
    )
    h = svc.create_study("a", seed=3)
    refusals = iter([77.0, 2.0])
    orig = svc._submit

    def flaky(study, timeout=None, replay=None):
        try:
            ra = next(refusals)
        except StopIteration:
            return orig(study, timeout=timeout, replay=replay)
        raise Overloaded("busy", retry_after=ra, reason="queue_full")

    monkeypatch.setattr(svc, "_submit", flaky)
    sleeps = []
    monkeypatch.setattr(time, "sleep", lambda s: sleeps.append(s))
    tid, vals = h.ask(timeout=60.0, backoff=True)
    assert vals
    # capped hint first, then the modest hint verbatim
    assert sleeps == [RETRY_AFTER_CAP, 2.0]
    svc.shutdown()


def test_router_draining_retry_sleeps_capped_and_stays_typed(monkeypatch):
    from hyperopt_tpu.serve import router as router_mod
    from hyperopt_tpu.serve.router import RouterServer, _Backend

    r = RouterServer([_Backend("b0", "127.0.0.1", 1)])
    draining = {
        "ok": False, "error_type": "Overloaded", "reason": "draining",
        "retry_after": 123.0, "error": "draining for restart",
    }
    monkeypatch.setattr(
        r, "_rpc", lambda conns, rid, req, timeout=30.0: dict(draining)
    )
    sleeps = []
    monkeypatch.setattr(
        router_mod.time, "sleep", lambda s: sleeps.append(s)
    )
    reply = r.handle_request({"op": "ask", "study": "s"}, {})
    # the backend outlasted the retry budget: the TYPED backpressure
    # reaches the client (whose own backoff owns the longer wait)
    assert reply["error_type"] == "Overloaded"
    assert reply["reason"] == "draining"
    assert sleeps and all(s == RETRY_AFTER_CAP for s in sleeps)


# ---------------------------------------------------------------------------
# co-batching: the shared-service registry
# ---------------------------------------------------------------------------


def test_ask_ahead_clamped_to_study_queue_cap():
    svc = SuggestService(
        SPACE, max_batch=8, background=False, n_startup_jobs=2,
        study_queue_cap=3, **ALGO_KW,
    )
    client, _, _, _ = _connect_client(svc, ask_ahead=99, max_submits=5)
    assert client.ask_ahead == 3  # an unclamped window would spin the
    client.finalize()             # backoff loop against the cap
    svc.shutdown()


def test_explicit_engine_hosts_multiple_clients():
    """The retired max_batch=1 regime's other half: a caller-provided
    engine now hosts N client studies (fmin, fmin-2, ...) instead of
    refusing the second connect."""
    svc = SuggestService(
        SPACE, max_batch=8, background=False, n_startup_jobs=2,
        **ALGO_KW,
    )
    c1, _, _, _ = _connect_client(svc, ask_ahead=1, max_submits=5)
    c2, _, _, _ = _connect_client(svc, ask_ahead=1, max_submits=5)
    assert c1.study_name == "fmin"
    assert c2.study_name == "fmin-2"
    c1.finalize()
    c2.finalize()
    svc.shutdown()


def test_concurrent_fmin_cobatch_one_service_bitwise_solo():
    """The tentpole: overlapping ``fmin(engine=True)`` calls of one
    study family ride ONE service, and every stream is bitwise the
    solo sequential run with the same rstate seed."""
    import hyperopt_tpu.serve as serve
    from hyperopt_tpu import client as client_mod
    from hyperopt_tpu import fmin

    seeds = [7, 8, 9]
    n_evals = 8

    def run_one(seed, objective):
        t = Trials()
        fmin(
            objective, SPACE, algo=tpe_jax.suggest, max_evals=n_evals,
            trials=t, rstate=np.random.default_rng(seed), engine=True,
            show_progressbar=False,
        )
        return [d["result"]["loss"] for d in t.trials]

    solo = {s: run_one(s, loss_fn) for s in seeds}
    assert not client_mod._SHARED_SERVICES  # sequential: drained

    built = []
    orig_init = serve.SuggestService.__init__

    def counting_init(self, *a, **kw):
        built.append(1)
        return orig_init(self, *a, **kw)

    gate = threading.Barrier(len(seeds), timeout=120)
    first_wave = threading.Semaphore(len(seeds))

    def overlapping(vals):
        if first_wave.acquire(blocking=False):
            gate.wait()  # force all three runs to overlap temporally
        return loss_fn(vals)

    results = {}
    serve.SuggestService.__init__ = counting_init
    try:
        threads = [
            threading.Thread(
                target=lambda s=s: results.update(
                    {s: run_one(s, overlapping)}
                )
            )
            for s in seeds
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
    finally:
        serve.SuggestService.__init__ = orig_init
    assert len(built) == 1, f"{len(built)} services for {len(seeds)} fmins"
    assert not client_mod._SHARED_SERVICES  # last client out cleaned up
    for s in seeds:
        assert results[s] == solo[s], f"seed {s} diverged from solo"


# ---------------------------------------------------------------------------
# graftstorm twins: the TCP round-trips again, under a seeded storm
# ---------------------------------------------------------------------------


def _storm_roundtrip(net_plan=None, front_plan=None, seed=3, rounds=6):
    """The pipelined TCP round-trip driven through the retrying
    ``RemoteStudy`` client: the clean run and its storm twin share
    this driver, so any divergence is the storm's."""
    from hyperopt_tpu.client import RemoteStudy

    svc = SuggestService(
        SPACE, background=True, max_batch=8, n_startup_jobs=2, **ALGO_KW,
    )
    srv = serve_forever(svc, port=0, net_plan=front_plan)
    _spawn(srv)
    host, port = srv.server_address[:2]
    try:
        c = RemoteStudy(
            host, port, "s", seed=seed, net_plan=net_plan,
            read_timeout=10.0,
        )
        stream = []
        for _ in range(rounds):
            tid, vals = c.ask(timeout=30)
            c.tell(tid, loss_fn(vals), vals)
            stream.append((tid, json.dumps(vals, sort_keys=True)))
        stats = dict(c.stats)
        count = int(svc.scheduler.study("s").buf.count)
        c.close()
        return stream, count, stats
    finally:
        _teardown(svc, srv)


def test_client_wire_storm_twin_bitwise_clean_run():
    """Default-off NetFaultPlan armed on the CLIENT wire of the TCP
    round-trip: resets mid-frame, latency, truncate-then-close -- the
    recover/re-tell discipline lands every op exactly once and the
    stream is bitwise the clean run's."""
    from hyperopt_tpu.distributed.faults import NetFaultPlan

    clean_stream, clean_count, clean_stats = _storm_roundtrip()
    assert clean_stats.get("transport_errors", 0) == 0
    plan = NetFaultPlan(
        seed=11, reset_rate=0.15, latency=0.001, truncate_rate=0.1,
        burst=2,
    )
    stream, count, stats = _storm_roundtrip(net_plan=plan)
    assert stream == clean_stream  # the storm is stream-invisible
    assert count == clean_count == 6
    assert (
        plan.stats.get("net:reset", 0) + plan.stats.get("net:truncate", 0)
    ) > 0, "the storm never actually injected"
    assert stats["transport_errors"] > 0  # ...and the client absorbed it


def test_server_front_storm_twin_bitwise_clean_run():
    """The same storm injected on the SERVER front's accepted
    connections (``serve_forever(net_plan=...)``'s wrap_pair seam):
    torn replies and reset reads surface as transport errors the
    client retries through -- exactly-once, bitwise."""
    from hyperopt_tpu.distributed.faults import NetFaultPlan

    clean_stream, clean_count, _ = _storm_roundtrip(seed=5, rounds=5)
    plan = NetFaultPlan(
        seed=12, reset_rate=0.12, latency=0.001, truncate_rate=0.08,
        burst=2,
    )
    stream, count, stats = _storm_roundtrip(
        front_plan=plan, seed=5, rounds=5
    )
    assert stream == clean_stream
    assert count == clean_count == 5
    assert (
        plan.stats.get("net:reset", 0) + plan.stats.get("net:truncate", 0)
    ) > 0, "the storm never actually injected"
    assert stats["transport_errors"] > 0


# ---------------------------------------------------------------------------
# CI gates: the burst modules stay lint- and trace-clean
# ---------------------------------------------------------------------------


def test_burst_modules_lint_and_trace_clean():
    from hyperopt_tpu.analysis import lint_paths

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [
        os.path.join(repo, "hyperopt_tpu", "serve", "frames.py"),
        os.path.join(repo, "hyperopt_tpu", "serve", "scheduler.py"),
        os.path.join(repo, "hyperopt_tpu", "serve", "service.py"),
        os.path.join(repo, "hyperopt_tpu", "serve", "router.py"),
        os.path.join(repo, "hyperopt_tpu", "utils", "wal.py"),
        os.path.join(repo, "hyperopt_tpu", "client.py"),
    ]
    for pack in ("ast", "trace"):
        result = lint_paths(paths, pack=pack)
        assert not result.findings, (pack, result.findings)


# ---------------------------------------------------------------------------
# the 10^4-client soak (slow tier; BENCH_BURST_SOAK_* sized)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_thousand_client_soak_typed_errors_only():
    """10^4 binary pipelining clients against one served engine, a
    worker pool deep (ROADMAP item 1's sustained-fleet scale; size via
    ``BENCH_BURST_SOAK_CLIENTS``/``BENCH_BURST_SOAK_POOL``): every
    reply is ok or a TYPED error (Overloaded / DeadlineExpired
    backpressure is the signal, never a raw traceback, never a hang),
    with lockdep armed the whole way."""
    svc, srv = _tcp_service(max_queue=4096, study_queue_cap=64)
    addr = srv.server_address[:2]
    names = [f"s{i}" for i in range(8)]
    for i, n in enumerate(names):
        svc.create_study(n, seed=i)
    failures = []
    counted = threading.Lock()
    stats = {"ok": 0, "typed": 0}

    def one_client(i):
        try:
            sock = socket.create_connection(addr, timeout=60)
        except OSError as e:
            failures.append(("connect", i, str(e)))
            return
        try:
            conn = FrameConn(sock.makefile("rwb"))
            name = names[i % len(names)]
            fut = conn.submit({"op": "ask", "study": name, "timeout": 45})
            r = conn.drain(fut)
            if r.get("ok"):
                t = conn.call({
                    "op": "tell", "study": name, "tid": r["tid"],
                    "loss": 0.1 + (i % 10) / 100.0,
                })
                if not t.get("ok") and not t.get("error_type"):
                    failures.append(("tell", i, t))
                with counted:
                    stats["ok"] += 1
            elif r.get("error_type"):
                with counted:
                    stats["typed"] += 1  # backpressure: the contract
            else:
                failures.append(("ask", i, r))
            conn.close()
        except Exception as e:  # noqa: BLE001 -- any raw client crash fails the soak
            failures.append(("client", i, f"{type(e).__name__}: {e}"))
        finally:
            sock.close()

    n_clients = int(os.environ.get("BENCH_BURST_SOAK_CLIENTS", "10000"))
    pool_width = int(os.environ.get("BENCH_BURST_SOAK_POOL", "64"))
    idx = iter(range(n_clients))
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                i = next(idx, None)
            if i is None:
                return
            one_client(i)

    workers = [threading.Thread(target=worker) for _ in range(pool_width)]
    for w in workers:
        w.start()
    for w in workers:
        w.join(timeout=900)
    try:
        assert not failures, failures[:10]
        assert stats["ok"] + stats["typed"] == n_clients
        assert stats["ok"] > 0
    finally:
        _teardown(svc, srv)
