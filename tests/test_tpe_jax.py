"""End-to-end tests for the jitted TPE path (tpe_jax.suggest as a drop-in
algo; JaxTrials buffers; batched suggest) -- the north-star seam."""

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp, rand
from hyperopt_tpu import tpe_jax, rand_jax
from hyperopt_tpu.jax_trials import JaxTrials, ObsBuffer, obs_buffer_for
from hyperopt_tpu.ops.compile import compile_space


def quad(x):
    return (x - 3.0) ** 2


SPACE = hp.uniform("x", -10, 10)


def test_rand_jax_end_to_end():
    trials = Trials()
    best = fmin(
        quad, SPACE, algo=rand_jax.suggest, max_evals=30, trials=trials,
        rstate=np.random.default_rng(0), show_progressbar=False,
    )
    assert len(trials) == 30
    assert -10 <= best["x"] <= 10


@pytest.mark.slow
def test_tpe_jax_beats_random_on_quadratic():
    def run(algo, seed):
        trials = Trials()
        fmin(
            quad, SPACE, algo=algo, max_evals=70, trials=trials,
            rstate=np.random.default_rng(seed), show_progressbar=False,
        )
        return trials.best_trial["result"]["loss"]

    tpe_losses = [run(tpe_jax.suggest, s) for s in (0, 1)]
    rand_losses = [run(rand.suggest, s) for s in (0, 1)]
    assert np.median(tpe_losses) <= np.median(rand_losses)
    assert min(tpe_losses) < 0.1


def test_tpe_jax_conditional_space():
    space = hp.choice(
        "c",
        [
            {"kind": "a", "lr": hp.loguniform("lr_a", -5, 0)},
            {"kind": "b", "x": hp.uniform("x_b", 0, 1), "n": hp.randint("n_b", 5)},
        ],
    )

    def obj(cfg):
        return cfg["lr"] if cfg["kind"] == "a" else cfg["x"]

    trials = Trials()
    best = fmin(
        obj, space, algo=tpe_jax.suggest, max_evals=50, trials=trials,
        rstate=np.random.default_rng(2), show_progressbar=False,
    )
    # structural integrity of every suggested trial
    for t in trials.trials:
        vals = t["misc"]["vals"]
        c = vals["c"][0]
        if c == 0:
            assert vals["lr_a"] and not vals["x_b"] and not vals["n_b"]
        else:
            assert vals["x_b"] and vals["n_b"] and not vals["lr_a"]
            assert isinstance(vals["n_b"][0], int)
    assert trials.best_trial["result"]["loss"] < 0.5


def test_tpe_jax_batched_suggest():
    trials = JaxTrials()
    fmin(
        quad, SPACE, algo=tpe_jax.suggest, max_evals=80, trials=trials,
        max_queue_len=16, rstate=np.random.default_rng(3),
        show_progressbar=False,
    )
    assert len(trials) == 80
    assert trials.best_trial["result"]["loss"] < 1.0


def test_tpe_jax_mixed_int_space():
    space = {
        "u": hp.uniform("u", -5, 5),
        "q": hp.quniform("q", 0, 10, 1),
        "r": hp.randint("r", 4),
    }

    def obj(cfg):
        return (cfg["u"] - 1) ** 2 / 10 + abs(cfg["q"] - 5) / 5 + cfg["r"] * 0.1

    trials = Trials()
    fmin(
        obj, space, algo=tpe_jax.suggest, max_evals=45, trials=trials,
        rstate=np.random.default_rng(4), show_progressbar=False,
    )
    for t in trials.trials:
        vals = t["misc"]["vals"]
        assert isinstance(vals["r"][0], int) and 0 <= vals["r"][0] < 4
        assert float(vals["q"][0]).is_integer()
    assert trials.best_trial["result"]["loss"] < 1.5


def test_obs_buffer_sync_and_growth():
    ps = compile_space(SPACE)
    buf = ObsBuffer(ps, capacity=4)
    trials = Trials()
    docs = []
    for tid in range(10):
        misc = {"tid": tid, "cmd": None, "idxs": {"x": [tid]}, "vals": {"x": [float(tid)]}}
        (d,) = trials.new_trial_docs(
            [tid], [None], [{"status": "ok", "loss": float(tid)}], [misc]
        )
        d["state"] = 2
        docs.append(d)
    trials.insert_trial_docs(docs[:3])
    trials.refresh()
    assert buf.sync(trials) == 3
    assert buf.count == 3 and buf.capacity == 4
    trials.insert_trial_docs(docs[3:])
    trials.refresh()
    assert buf.sync(trials) == 7  # incremental: only the new ones
    assert buf.count == 10 and buf.capacity == 16  # grew 4 -> 16 (one 4x step)
    np.testing.assert_array_equal(buf.losses[:10], np.arange(10, dtype=np.float32))
    assert buf.valid[:10].all() and not buf.valid[10:].any()


def test_obs_buffer_skips_failed_and_nan():
    ps = compile_space(SPACE)
    trials = Trials()
    entries = [
        ({"status": "ok", "loss": 1.0}, 2),
        ({"status": "fail"}, 2),
        ({"status": "ok", "loss": float("nan")}, 2),
        ({"status": "ok", "loss": 2.0}, 3),  # JOB_STATE_ERROR
        ({"status": "ok", "loss": 3.0}, 2),
    ]
    docs = []
    for tid, (result, state) in enumerate(entries):
        misc = {"tid": tid, "cmd": None, "idxs": {"x": [tid]}, "vals": {"x": [0.1]}}
        (d,) = trials.new_trial_docs([tid], [None], [result], [misc])
        d["state"] = state
        docs.append(d)
    trials.insert_trial_docs(docs)
    trials.refresh()
    buf = ObsBuffer(ps)
    assert buf.sync(trials) == 2  # only the two finite ok/DONE trials
    np.testing.assert_array_equal(buf.losses[:2], [1.0, 3.0])


def test_jax_trials_buffer_reuse_and_pickle():
    import pickle

    trials = JaxTrials()
    fmin(
        quad, SPACE, algo=tpe_jax.suggest, max_evals=25, trials=trials,
        rstate=np.random.default_rng(5), show_progressbar=False,
    )
    assert len(trials._buffers) == 1
    blob = pickle.dumps(trials)
    revived = pickle.loads(blob)
    assert len(revived) == 25
    assert revived._buffers == {}  # derived state dropped, rebuilt on demand
    from hyperopt_tpu.base import Domain

    domain = Domain(quad, SPACE)
    buf = obs_buffer_for(domain, revived)
    assert buf.count == 25


def test_tpe_jax_reproducible():
    def run():
        trials = Trials()
        fmin(
            quad, SPACE, algo=tpe_jax.suggest, max_evals=30, trials=trials,
            rstate=np.random.default_rng(7), show_progressbar=False,
        )
        return [t["misc"]["vals"]["x"][0] for t in trials.trials]

    assert run() == run()


@pytest.mark.slow
def test_tpe_jax_joint_ei_conditional_space():
    """joint_ei=True scores whole configurations; draws must still respect
    bounds, types, and conditional activity, and be deterministic."""
    from functools import partial

    space = {
        "x": hp.uniform("x", -5, 5),
        "arch": hp.choice(
            "arch",
            [
                {"k": 0, "depth": hp.randint("depth", 2, 8)},
                {"k": 1, "w": hp.quniform("w", 0, 10, 1)},
            ],
        ),
    }

    def obj(cfg):
        a = cfg["arch"]
        extra = 0.1 * (a["depth"] - 5) ** 2 if a["k"] == 0 else a["w"] * 0.01
        return cfg["x"] ** 2 + extra

    algo = partial(tpe_jax.suggest, joint_ei=True, n_startup_jobs=10)

    def run():
        trials = Trials()
        fmin(
            obj, space, algo=algo, max_evals=40, trials=trials,
            rstate=np.random.default_rng(11), show_progressbar=False,
        )
        return trials

    trials = run()
    assert len(trials) == 40
    for t in trials.trials:
        vals = t["misc"]["vals"]
        (x,) = vals["x"]
        assert -5 <= x <= 5
        (arm,) = vals["arch"]
        if arm == 0:
            (depth,) = vals["depth"]
            assert 2 <= depth < 8 and vals["w"] == []
        else:
            (w,) = vals["w"]
            assert w == round(w) and 0 <= w <= 10 and vals["depth"] == []
    assert trials.losses() == run().losses()  # fixed seed -> identical


@pytest.mark.slow
def test_tpe_jax_joint_ei_beats_random_on_correlated():
    """Whole-configuration scoring handles a correlated objective: loss
    depends on x + y, which the factorized marginals cannot represent."""
    from functools import partial

    space = {"x": hp.uniform("x", -5, 5), "y": hp.uniform("y", -5, 5)}

    def obj(cfg):
        return (cfg["x"] + cfg["y"] - 1.0) ** 2

    def best_with(algo):
        outs = []
        for seed in (0, 1, 2, 3):
            trials = Trials()
            fmin(
                obj, space, algo=algo, max_evals=60, trials=trials,
                rstate=np.random.default_rng(seed), show_progressbar=False,
            )
            outs.append(min(trials.losses()))
        # MEDIAN over seeds, not mean: random search occasionally lands
        # one lucky startup draw (seed 1 hits 6.5e-4 inside the shared
        # 20-trial startup stream) and a 2-seed mean let that single
        # outlier decide the comparison (FAILURES.md "known test debt");
        # the median pins the typical-case ordering deterministically
        return float(np.median(outs))

    joint = best_with(partial(tpe_jax.suggest, joint_ei=True))
    random = best_with(rand_jax.suggest)
    assert joint < random, (joint, random)


@pytest.mark.slow
def test_tpe_jax_wide_space_68_labels():
    """Scaling smoke: a 68-label mixed space (24 uniform, 12 loguniform,
    8 quantized, 12 flat choices, 4 nested choices) compiles and
    optimizes end-to-end."""
    space = {}
    for i in range(24):
        space[f"u{i}"] = hp.uniform(f"u{i}", -1, 1)
    for i in range(12):
        space[f"l{i}"] = hp.loguniform(f"l{i}", -5, 1)
    for i in range(8):
        space[f"q{i}"] = hp.quniform(f"q{i}", 0, 20, 1)
    for i in range(12):
        space[f"c{i}"] = hp.choice(f"c{i}", list(range(4)))
    for i in range(4):
        space[f"nest{i}"] = hp.choice(f"nest{i}", [
            {"k": 0, "a": hp.uniform(f"na{i}", 0, 1)},
            {"k": 1, "b": hp.randint(f"nb{i}", 5)},
        ])

    def obj(cfg):
        loss = sum(cfg[f"u{i}"] ** 2 for i in range(24))
        return loss + sum(abs(cfg[f"c{i}"] - 1) for i in range(12)) * 0.1

    trials = Trials()
    fmin(obj, space, algo=tpe_jax.suggest, max_evals=50, trials=trials,
         rstate=np.random.default_rng(0), show_progressbar=False)
    assert len(trials) == 50
    assert np.isfinite(min(trials.losses()))
    # every trial carries exactly one branch per nested choice
    for t in trials.trials:
        vals = t["misc"]["vals"]
        for i in range(4):
            arm = vals[f"nest{i}"][0]
            assert (len(vals[f"na{i}"]) == 1) == (arm == 0)
            assert (len(vals[f"nb{i}"]) == 1) == (arm == 1)


# ---------------------------------------------------------------------------
# speculative batching (one dispatch serves k sequential asks)
# ---------------------------------------------------------------------------


def test_speculative_serves_follow_ups_from_cache(monkeypatch):
    """k-wide speculation: 1 dense draw per k asks while history is
    unchanged; a new completed observation beyond max_stale invalidates."""
    from functools import partial

    from hyperopt_tpu.base import Domain, JOB_STATE_DONE

    domain = Domain(quad, SPACE)
    trials = Trials()
    # seed history past startup so the TPE path runs
    docs = rand.suggest(trials.new_trial_ids(25), domain, trials, seed=0)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float(doc["tid"])}
    trials.insert_trial_docs(docs)
    trials.refresh()

    calls = []
    real_dense = tpe_jax.suggest_dense

    def counting_dense(*a, **kw):
        calls.append(a[3])  # batch arg
        return real_dense(*a, **kw)

    monkeypatch.setattr(tpe_jax, "suggest_dense", counting_dense)
    algo = partial(tpe_jax.suggest, speculative=4)

    out_docs = []
    for i in range(4):
        out_docs += algo(trials.new_trial_ids(1), domain, trials, seed=100 + i)
    assert calls == [4]  # ONE dispatch for four asks
    xs = [d["misc"]["vals"]["x"][0] for d in out_docs]
    assert len(set(xs)) == 4  # four distinct suggestions, not one repeated

    # fifth ask: cache drained -> fresh dispatch
    algo(trials.new_trial_ids(1), domain, trials, seed=200)
    assert calls == [4, 4]

    # a partial differing in max_stale keys its OWN cache entry (it must
    # never pop columns drawn under another staleness policy) ...
    strict = partial(tpe_jax.suggest, speculative=4, max_stale=0)
    strict(trials.new_trial_ids(1), domain, trials, seed=300)
    assert calls == [4, 4, 4]
    # ... and with unchanged history even max_stale=0 serves follow-ups
    # from its warm cache
    strict(trials.new_trial_ids(1), domain, trials, seed=301)
    assert calls == [4, 4, 4]
    # one new completed observation > max_stale=0 -> invalidated, fresh
    # dispatch even though the cache still holds unserved columns
    new = rand.suggest(trials.new_trial_ids(1), domain, trials, seed=1)
    new[0]["state"] = JOB_STATE_DONE
    new[0]["result"] = {"status": "ok", "loss": 0.5}
    trials.insert_trial_docs(new)
    trials.refresh()
    strict(trials.new_trial_ids(1), domain, trials, seed=302)
    assert calls == [4, 4, 4, 4]


def test_speculative_cache_keyed_by_max_stale(monkeypatch):
    """Partials differing ONLY in max_stale must not pop each other's
    cached columns: the resolved staleness budget is part of the cache
    key (two policies sharing one k-wide draw would silently apply the
    wrong invalidation rule to each other's columns)."""
    from functools import partial

    from hyperopt_tpu.base import Domain, JOB_STATE_DONE

    domain = Domain(quad, SPACE)
    trials = Trials()
    docs = rand.suggest(trials.new_trial_ids(25), domain, trials, seed=0)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float(doc["tid"])}
    trials.insert_trial_docs(docs)
    trials.refresh()

    calls = []
    real_dense = tpe_jax.suggest_dense

    def counting_dense(*a, **kw):
        calls.append(a[3])
        return real_dense(*a, **kw)

    monkeypatch.setattr(tpe_jax, "suggest_dense", counting_dense)
    relaxed = partial(tpe_jax.suggest, speculative=4)  # max_stale=3
    strict = partial(tpe_jax.suggest, speculative=4, max_stale=0)

    relaxed(trials.new_trial_ids(1), domain, trials, seed=1)
    assert calls == [4]
    strict(trials.new_trial_ids(1), domain, trials, seed=2)
    assert calls == [4, 4]  # its own draw, not a pop of relaxed's cache
    # both partials keep serving follow-ups from their OWN entries
    relaxed(trials.new_trial_ids(1), domain, trials, seed=3)
    strict(trials.new_trial_ids(1), domain, trials, seed=4)
    assert calls == [4, 4]


def test_speculative_auto_degrades_on_saturated_categorical(monkeypatch):
    """VERDICT r2 weak #4: on a pure-categorical space whose candidate
    draw covers every option the EI argmax is deterministic, so the k
    columns of a speculative draw are near-duplicates evaluated k times.
    The regime is detected at build time and speculation auto-degrades
    to one dispatch per ask (one-time warning); the emitted suggestions
    are exactly the non-speculative path's -- quality returns to the
    non-speculative baseline by construction."""
    import warnings
    from functools import partial

    from hyperopt_tpu.base import Domain, JOB_STATE_DONE
    from hyperopt_tpu.models import nasbench

    domain = Domain(nasbench.objective, nasbench.space())
    trials = Trials()
    docs = rand.suggest(trials.new_trial_ids(25), domain, trials, seed=0)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
        cfg = {k: v[0] for k, v in doc["misc"]["vals"].items()}
        doc["result"] = {"status": "ok", "loss": nasbench.objective(cfg)}
    trials.insert_trial_docs(docs)
    trials.refresh()

    calls = []
    real_dense = tpe_jax.suggest_dense

    def counting_dense(*a, **kw):
        calls.append(a[3])
        return real_dense(*a, **kw)

    monkeypatch.setattr(tpe_jax, "suggest_dense", counting_dense)
    algo = partial(tpe_jax.suggest, speculative=8)
    spec_out = []
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for i in range(4):
            (d,) = algo(trials.new_trial_ids(1), domain, trials, seed=50 + i)
            spec_out.append(d["misc"]["vals"])
    assert calls == [1, 1, 1, 1]  # one dispatch PER ask, no k-wide draw
    msgs = [str(w.message) for w in caught if "speculative" in str(w.message)]
    assert len(msgs) == 1  # warned exactly once per domain

    # parity: the degraded path IS the non-speculative path (same seeds,
    # same unchanged history -> identical suggestions)
    plain_out = []
    for i in range(4):
        (d,) = tpe_jax.suggest(
            trials.new_trial_ids(1), domain, trials, seed=50 + i
        )
        plain_out.append(d["misc"]["vals"])
    assert spec_out == plain_out

    # a MIXED space (any continuous dim) must keep speculating
    mixed_domain = Domain(quad, SPACE)
    mixed_trials = Trials()
    mdocs = rand.suggest(
        mixed_trials.new_trial_ids(25), mixed_domain, mixed_trials, seed=0
    )
    for doc in mdocs:
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float(doc["tid"])}
    mixed_trials.insert_trial_docs(mdocs)
    mixed_trials.refresh()
    calls.clear()
    for i in range(4):
        algo(mixed_trials.new_trial_ids(1), mixed_domain, mixed_trials,
             seed=70 + i)
    assert calls == [8]  # one 8-wide dispatch serves all four asks


def test_speculative_rand_and_atpe_paths(monkeypatch):
    """Every per-trial JAX algo shares the speculation story: rand_jax
    serves k asks per prior dispatch (never stale), and atpe_jax serves
    k asks per adaptive draw with the tpe staleness semantics."""
    from functools import partial

    from hyperopt_tpu import atpe_jax
    from hyperopt_tpu.base import Domain, JOB_STATE_DONE

    # rand_jax: count prior dispatches via its dense-draw helper
    domain = Domain(quad, SPACE)
    trials = Trials()
    calls = []
    real_draw = rand_jax._dense_draw

    def counting_draw(domain_, seed_, batch):
        calls.append(batch)
        return real_draw(domain_, seed_, batch)

    monkeypatch.setattr(rand_jax, "_dense_draw", counting_draw)
    algo = partial(rand_jax.suggest, speculative=4)
    out = []
    for i in range(4):
        (d,) = algo(trials.new_trial_ids(1), domain, trials, seed=10 + i)
        out.append(d["misc"]["vals"]["x"][0])
    assert calls == [4]  # ONE prior dispatch for four asks
    assert len(set(out)) == 4  # distinct draws, not one repeated
    # prior never goes stale: new completed trials don't invalidate
    docs = rand.suggest(trials.new_trial_ids(2), domain, trials, seed=0)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": 1.0}
    trials.insert_trial_docs(docs)
    trials.refresh()
    algo(trials.new_trial_ids(1), domain, trials, seed=20)
    assert calls == [4, 4]  # drained cache -> fresh dispatch, same width
    monkeypatch.setattr(rand_jax, "_dense_draw", real_draw)

    # atpe_jax: count device draws via suggest_dense (warm history)
    domain2 = Domain(quad, SPACE)
    trials2 = Trials()
    docs = rand.suggest(trials2.new_trial_ids(25), domain2, trials2, seed=0)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float(doc["tid"])}
    trials2.insert_trial_docs(docs)
    trials2.refresh()
    dense_calls = []
    real_dense = tpe_jax.suggest_dense

    def counting_dense(*a, **kw):
        dense_calls.append(a[3])
        return real_dense(*a, **kw)

    monkeypatch.setattr(tpe_jax, "suggest_dense", counting_dense)
    aalgo = partial(atpe_jax.suggest, speculative=4)
    for i in range(4):
        aalgo(trials2.new_trial_ids(1), domain2, trials2, seed=30 + i)
    assert dense_calls == [4]  # one adaptive draw serves four asks


@pytest.mark.slow
def test_speculative_fmin_quality_and_structure():
    """End-to-end fmin with speculative asks: same quality profile as
    max_queue_len batching, valid trial docs, beats random."""
    from functools import partial

    def run(algo, seed):
        trials = Trials()
        fmin(
            quad, SPACE, algo=algo, max_evals=70, trials=trials,
            rstate=np.random.default_rng(seed), show_progressbar=False,
        )
        assert len(trials) == 70
        for t in trials.trials:
            assert len(t["misc"]["vals"]["x"]) == 1
        return trials.best_trial["result"]["loss"]

    spec = partial(tpe_jax.suggest, speculative=8)
    spec_losses = [run(spec, s) for s in (0, 1)]
    rand_losses = [run(rand.suggest, s) for s in (0, 1)]
    assert np.median(spec_losses) <= np.median(rand_losses)
    assert min(spec_losses) < 0.35


@pytest.mark.slow
def test_speculative_reproducible():
    from functools import partial

    def run():
        trials = Trials()
        fmin(
            quad, SPACE, algo=partial(tpe_jax.suggest, speculative=4),
            max_evals=40, trials=trials,
            rstate=np.random.default_rng(7), show_progressbar=False,
        )
        return trials.losses()

    assert run() == run()


@pytest.mark.slow
def test_joint_ei_battery_vs_factorized():
    """The joint_ei verdict (measured, 5 seeds, round 2): whole-config
    scoring NEVER materially beats factorized EI -- candidates come from
    the same factorized marginals either way, and the factorized per-dim
    argmax optimizes the additive acquisition at least as well (medians:
    corr_sum 0.0017 joint vs 0.0019 fact; rosenbrock2 0.149 vs 0.049
    fact wins; gauss_wave2 -1.468 vs -1.487 fact wins).  Default stays
    OFF (reference parity).  This test pins the quality floor of the
    joint path on two correlated-optimum configs: it must keep beating
    random and stay within a modest margin of factorized."""
    from functools import partial

    from hyperopt_tpu.models.synthetic import DOMAINS

    corr_space = {"x": hp.uniform("cx", -5, 5), "y": hp.uniform("cy", -5, 5)}

    def corr_fn(cfg):
        return (cfg["x"] + cfg["y"] - 1.0) ** 2

    gw = DOMAINS["gauss_wave2"]

    def med(algo, fn, mkspace, n):
        outs = []
        for seed in (0, 1, 2):
            trials = Trials()
            fmin(fn, mkspace() if callable(mkspace) else mkspace, algo=algo,
                 max_evals=n, trials=trials,
                 rstate=np.random.default_rng(seed), show_progressbar=False,
                 return_argmin=False)
            outs.append(min(trials.losses()))
        return float(np.median(outs))

    joint = partial(tpe_jax.suggest, joint_ei=True)

    j = med(joint, corr_fn, lambda: corr_space, 80)
    f = med(tpe_jax.suggest, corr_fn, lambda: corr_space, 80)
    r = med(rand.suggest, corr_fn, lambda: corr_space, 80)
    assert j < r, (j, r)
    assert j <= max(2.0 * f, f + 0.01), (j, f)

    j2 = med(joint, gw.fn, gw.make_space, 100)
    f2 = med(tpe_jax.suggest, gw.fn, gw.make_space, 100)
    assert j2 < -1.35, j2            # far below random's ~-1.27
    assert j2 <= f2 + 0.08, (j2, f2)


# ---------------------------------------------------------------------------
# async-mode observation ingestion (round-2 bug regression)
# ---------------------------------------------------------------------------


def _insert_new(trials, domain, n, seed):
    from hyperopt_tpu import rand

    docs = rand.suggest(trials.new_trial_ids(n), domain, trials, seed=seed)
    trials.insert_trial_docs(docs)
    trials.refresh()
    # return the STORED docs (insert may copy) so completion mutates
    # what refresh/sync actually see -- the async-backend pattern
    tids = {d["tid"] for d in docs}
    return [t for t in trials._dynamic_trials if t["tid"] in tids]


def _complete(trials, docs, loss):
    from hyperopt_tpu.base import JOB_STATE_DONE

    for d in docs:
        d["state"] = JOB_STATE_DONE
        d["result"] = {"status": "ok", "loss": float(loss)}
    trials.refresh()


def test_obs_buffer_ingests_trials_completed_after_scan():
    """Async backends routinely let a suggest scan see in-flight trials;
    they must enter the posterior once they complete (the round-2 bug
    dropped them forever and silently starved async TPE)."""
    from hyperopt_tpu.base import Domain

    domain = Domain(quad, SPACE)
    trials = Trials()
    docs = _insert_new(trials, domain, 5, seed=0)
    buf = obs_buffer_for(domain, trials)  # scanned while NEW
    assert buf.count == 0
    _complete(trials, docs, 1.0)
    buf = obs_buffer_for(domain, trials)
    assert buf.count == 5


def test_obs_buffer_interleaved_async_completions_keep_tid_order():
    """Trials completing out of order across syncs: every completion is
    ingested exactly once and slots stay tid-ordered (the forgetting
    weights are positional -- host-path parity)."""
    from hyperopt_tpu.base import Domain, JOB_STATE_ERROR

    domain = Domain(quad, SPACE)
    trials = Trials()
    batch1 = _insert_new(trials, domain, 3, seed=1)  # tids 0..2
    buf = obs_buffer_for(domain, trials)
    assert buf.count == 0

    batch2 = _insert_new(trials, domain, 3, seed=2)  # tids 3..5
    _complete(trials, batch2, 2.0)  # NEWER trials complete FIRST
    buf = obs_buffer_for(domain, trials)
    assert buf.count == 3

    _complete(trials, batch1[1:], 1.0)  # older trials complete late
    batch1[0]["state"] = JOB_STATE_ERROR  # one never produces a loss
    trials.refresh()
    buf = obs_buffer_for(domain, trials)
    assert buf.count == 5
    # slots must be tid-ordered: tids 1,2 (loss 1.0) before 3,4,5 (2.0)
    np.testing.assert_allclose(buf.losses[:5], [1, 1, 2, 2, 2])
    assert not buf._pending  # error trial dropped from the revisit list

    # further syncs are stable no-ops
    assert buf.sync(trials) == 0
    assert buf.count == 5


def test_obs_buffer_10k_ingestion_soak():
    """VERDICT r2 item 8 (CI-sized guard for the 10k-obs soak): drive the
    real doc-ingestion path to 10,000 observations and pin the capacity
    and upload-bucket growth schedules plus sync incrementality.  The
    on-chip throughput rows live in BASELINE.md (examples/soak_10k.py);
    this test caps the host-path cost: the whole ingestion must stay
    well under a minute (quadratic rescans would blow it)."""
    import time as _time

    from hyperopt_tpu.base import Domain, JOB_STATE_DONE
    from hyperopt_tpu.models.synthetic import mixed_space, mixed_space_fn

    domain = Domain(mixed_space_fn, mixed_space())
    trials = Trials()
    rng = np.random.default_rng(0)
    buf = obs_buffer_for(domain, trials)
    caps, buckets = [buf.capacity], [buf._device_bucket()]
    t0 = _time.perf_counter()
    n = 0
    while n < 10_000:
        ids = trials.new_trial_ids(500)
        docs = rand.suggest(ids, domain, trials, seed=n)
        for doc in docs:
            doc["state"] = JOB_STATE_DONE
            doc["result"] = {"status": "ok", "loss": float(rng.uniform(0, 10))}
        trials.insert_trial_docs(docs)
        trials.refresh()
        n += 500
        added = buf.sync(trials)
        assert added == 500  # incremental: exactly the new docs enter
        if buf.capacity != caps[-1]:
            caps.append(buf.capacity)
        if buf._device_bucket() != buckets[-1]:
            buckets.append(buf._device_bucket())
    elapsed = _time.perf_counter() - t0
    assert buf.count == 10_000
    # 4x capacity growths and pow2 upload buckets, as documented
    assert caps == [128, 512, 2048, 8192, 32768]
    assert buckets == [128, 512, 1024, 2048, 4096, 8192, 16384]
    # slots stayed tid-ordered through every growth
    assert (np.diff(buf.tids[:10_000]) > 0).all()
    # capped runtime: linear ingestion, no quadratic rescans
    assert elapsed < 60, f"10k ingestion took {elapsed:.1f}s"


def test_checkpoint_preserves_pending_docs(tmp_path):
    """A checkpoint taken while async trials are in flight must revisit
    them after resume: _pending persists in the npz, else scanned-but-
    pending docs sit below _n_scanned forever (posterior starvation
    through the checkpoint path)."""
    from hyperopt_tpu.base import Domain
    from hyperopt_tpu.jax_trials import packed_space_for
    from hyperopt_tpu.utils.checkpoint import load_obs_buffer, save_obs_buffer

    domain = Domain(quad, SPACE)
    trials = Trials()
    done = _insert_new(trials, domain, 3, seed=0)
    _complete(trials, done, 1.0)
    _insert_new(trials, domain, 2, seed=1)  # stay NEW (in flight)
    buf = obs_buffer_for(domain, trials)
    assert buf.count == 3 and len(buf._pending) == 2

    path = str(tmp_path / "obs.npz")
    save_obs_buffer(buf, path)
    buf2 = load_obs_buffer(packed_space_for(domain), path)
    assert list(buf2._pending) == list(buf._pending)

    # the in-flight trials complete after resume: they must be ingested
    inflight = [trials._dynamic_trials[i] for i in buf2._pending]
    _complete(trials, inflight, 2.0)
    buf2.sync(trials)
    assert buf2.count == 5
    assert not buf2._pending


def test_legacy_checkpoint_without_tids_rebuilds_on_sync(tmp_path):
    """Pre-round-2 checkpoints carry no tids; the synthesized arange
    guess is wrong for non-contiguous histories (failed trials interleave
    tids), so the first sync against a store must rebuild from the doc
    list instead of trusting it for late-completion inserts."""
    from hyperopt_tpu.base import Domain
    from hyperopt_tpu.jax_trials import packed_space_for
    from hyperopt_tpu.utils.checkpoint import load_obs_buffer, save_obs_buffer

    domain = Domain(quad, SPACE)
    trials = Trials()
    docs = _insert_new(trials, domain, 4, seed=0)  # tids 0..3
    _complete(trials, [docs[0], docs[2]], 1.0)  # 0,2 done; 1,3 in flight
    buf = obs_buffer_for(domain, trials)
    assert buf.count == 2 and list(buf.tids[:2]) == [0, 2]

    path = str(tmp_path / "obs.npz")
    save_obs_buffer(buf, path)
    # strip tids+pending to simulate a legacy checkpoint file
    with np.load(path, allow_pickle=True) as data:
        legacy = {k: data[k] for k in data.files if k not in ("tids", "pending")}
    np.savez_compressed(path, **legacy)

    buf2 = load_obs_buffer(packed_space_for(domain), path)
    assert buf2._legacy_tids
    assert buf2.count == 2  # standalone, the loaded data is usable

    # first sync rebuilds from the doc list: true tids restored, so a
    # late completion inserts at the RIGHT slot (tid order preserved)
    buf2.sync(trials)
    assert list(buf2.tids[:2]) == [0, 2]
    _complete(trials, [docs[1]], 0.5)  # tid 1 completes late
    buf2.sync(trials)
    assert buf2.count == 3
    assert list(buf2.tids[:3]) == [0, 1, 2]
    np.testing.assert_allclose(buf2.losses[:3], [1.0, 0.5, 1.0])


def test_async_thread_trials_tpe_jax_posterior_not_starved():
    """End-to-end: async evaluation + the jitted TPE path must still
    feed the posterior (quality sanity: beats the all-prior regime)."""
    import time as _time

    from hyperopt_tpu.distributed import ThreadTrials

    def slow_quad(x):
        _time.sleep(0.01)
        return (x - 3.0) ** 2

    trials = ThreadTrials(parallelism=4)
    fmin(
        slow_quad, SPACE, algo=tpe_jax.suggest, max_evals=60,
        trials=trials, rstate=np.random.default_rng(5),
        show_progressbar=False, return_argmin=False,
    )
    assert len(trials) == 60
    # with the posterior working, late trials concentrate near x=3
    xs = [t["misc"]["vals"]["x"][0] for t in trials.trials]
    late_spread = float(np.median(np.abs(np.array(xs[40:]) - 3.0)))
    early_spread = float(np.median(np.abs(np.array(xs[:20]) - 3.0)))
    assert late_spread < early_spread
    assert min(trials.losses()) < 1.0


def test_obs_buffer_waits_out_worker_write_window():
    """An async worker stores state=DONE then result as two writes; a
    sync landing between them must keep the trial pending (not evict it
    as terminal-but-unusable) and ingest it on the next sync."""
    from hyperopt_tpu.base import Domain, JOB_STATE_DONE

    domain = Domain(quad, SPACE)
    trials = Trials()
    docs = _insert_new(trials, domain, 2, seed=0)
    # simulate the torn write: state flipped, result not yet posted
    docs[0]["state"] = JOB_STATE_DONE  # result still {"status": "new"}
    trials.refresh()
    buf = obs_buffer_for(domain, trials)
    assert buf.count == 0
    docs[0]["result"] = {"status": "ok", "loss": 0.5}
    docs[1]["state"] = JOB_STATE_DONE
    docs[1]["result"] = {"status": "ok", "loss": 1.5}
    trials.refresh()
    buf = obs_buffer_for(domain, trials)
    assert buf.count == 2
    np.testing.assert_allclose(buf.losses[:2], [0.5, 1.5])


def test_obs_buffer_domain_cache_keyed_by_trials_store():
    """One Domain reused across two Trials stores must never serve the
    first store's observations for the second."""
    from hyperopt_tpu.base import Domain

    domain = Domain(quad, SPACE)
    trials_a = Trials()
    docs = _insert_new(trials_a, domain, 4, seed=0)
    _complete(trials_a, docs, 1.0)
    buf_a = obs_buffer_for(domain, trials_a)
    assert buf_a.count == 4

    trials_b = Trials()
    docs_b = _insert_new(trials_b, domain, 6, seed=1)
    _complete(trials_b, docs_b, 2.0)
    buf_b = obs_buffer_for(domain, trials_b)
    assert buf_b.count == 6
    np.testing.assert_allclose(buf_b.losses[:6], [2.0] * 6)  # no mixing


def test_device_arrays_bucket_by_live_count():
    """device_arrays slices uploads to the pow2 bucket of the live count
    (padding bounded at 2x) instead of the 4x-grown capacity; the cache
    keys on (generation, bucket)."""
    ps = compile_space(SPACE)
    buf = ObsBuffer(ps, capacity=4)
    for i in range(300):
        buf.add({"x": float(i)}, float(i))
    assert buf.capacity == 1024  # 4 -> 16 -> 64 -> 256 -> 1024
    arrs = buf.device_arrays()
    assert arrs[0].shape == (1, 512)  # pow2 bucket of 300, not 1024
    assert arrs[2].shape == (512,)
    a0 = arrs[0]
    assert buf.device_arrays()[0] is a0  # cached while unchanged
    for i in range(300, 600):
        buf.add({"x": float(i)}, float(i))
    arrs = buf.device_arrays()
    assert arrs[0].shape == (1, 1024)  # crossed the bucket boundary
    np.testing.assert_allclose(
        np.asarray(arrs[2])[:600], np.arange(600, dtype=np.float32)
    )


def test_device_bucket_stops_pow2_rebucketing_past_compaction_cap():
    """Round 6: with an above-model compaction cap, the scoring width is
    static past the cap, so the device bucket stops growing at every
    pow2 crossing there and rides GROWTH_FACTOR steps instead -- fewer
    retraces at large histories, identical schedule below the cap."""
    ps = compile_space(SPACE)
    buf = ObsBuffer(ps)
    seen_plain, seen_capped = [], []
    for i in range(12_000):
        buf.add({"x": float(i % 7)}, float(i % 11))
        for seen, cap in ((seen_plain, None), (seen_capped, 512)):
            b = buf._device_bucket(pow2_cap=cap)
            if not seen or seen[-1] != b:
                seen.append(b)
    assert seen_plain == [128, 256, 512, 1024, 2048, 4096, 8192, 16384]
    # below the cap: identical; past it: one 4x step per growth
    assert seen_capped == [128, 256, 512, 2048, 8192, 32768]
    # the device view follows the capped bucket
    arrs = buf.device_arrays(pow2_cap=512)
    assert arrs[0].shape[1] == 32768


def _mixed_history(n_obs, seed=0):
    """A completed synthetic history on the 20-dim mixed space."""
    from hyperopt_tpu.base import Domain, JOB_STATE_DONE
    from hyperopt_tpu.models.synthetic import mixed_space, mixed_space_fn

    domain = Domain(mixed_space_fn, mixed_space())
    trials = Trials()
    rng = np.random.default_rng(seed)
    ids = trials.new_trial_ids(n_obs)
    docs = rand.suggest(ids, domain, trials, seed=seed)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float(rng.uniform(0, 10))}
    trials.insert_trial_docs(docs)
    trials.refresh()
    return domain, trials


def test_suggest_dense_above_cap_parity_below_cap():
    """ACCEPTANCE PIN (round 6): on a history whose above set fits under
    the compaction cap, the full suggest program (compacted) emits a
    BITWISE identical suggestion stream to full-width scoring -- the
    end-to-end form of the kernel-level parity pin.  50 obs in a
    128-wide bucket with cap 64: compaction is compiled in (width 129 >
    pad 64) but mathematically the identity."""
    domain, trials = _mixed_history(50)
    v_comp, a_comp = tpe_jax.suggest_dense(domain, trials, 7, 4,
                                           above_cap=64)
    v_full, a_full = tpe_jax.suggest_dense(domain, trials, 7, 4,
                                           above_cap=0)
    assert np.array_equal(np.asarray(v_comp), np.asarray(v_full))
    assert np.array_equal(np.asarray(a_comp), np.asarray(a_full))
    # the two settings trace distinct cached programs (the cap is part
    # of the compile-cache key: serving one for the other would be a
    # silent width mismatch)
    assert len(domain._tpe_jax_cache) == 2


def test_suggest_dense_compaction_past_cap_quality_sane():
    """Past the cap the stream may differ from full-width, but the
    draws must stay in-bounds, finite, and the posterior must still
    steer: on a quadratic with 700 completed obs, compacted TPE's
    suggestions concentrate far tighter around the optimum than the
    prior does."""
    from hyperopt_tpu.base import Domain, JOB_STATE_DONE

    domain = Domain(quad, SPACE)
    trials = Trials()
    rng = np.random.default_rng(3)
    ids = trials.new_trial_ids(700)
    docs = rand.suggest(ids, domain, trials, seed=0)
    for doc in docs:
        x = doc["misc"]["vals"]["x"][0]
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float(quad(x))}
    trials.insert_trial_docs(docs)
    trials.refresh()
    v, a = tpe_jax.suggest_dense(domain, trials, 11, 64, above_cap=128)
    xs = np.asarray(v)[0]
    assert np.isfinite(xs).all() and (xs >= -10).all() and (xs <= 10).all()
    # TPE spread around the optimum far under the prior's ~5.0
    assert float(np.median(np.abs(xs - 3.0))) < 2.0


def test_async_plus_speculative_combination():
    """The production mode for remote-attached chips: async evaluation
    (ThreadTrials) with speculative k-ahead suggests. Must complete,
    ingest every observation, and still optimize."""
    import time as _time
    from functools import partial

    from hyperopt_tpu.distributed import ThreadTrials

    def slow_quad(x):
        _time.sleep(0.005)
        return (x - 3.0) ** 2

    trials = ThreadTrials(parallelism=3)
    fmin(
        slow_quad, SPACE, algo=partial(tpe_jax.suggest, speculative=4),
        max_evals=50, trials=trials, rstate=np.random.default_rng(9),
        show_progressbar=False, return_argmin=False,
    )
    assert len(trials) == 50
    from hyperopt_tpu.base import JOB_STATE_DONE

    assert sum(t["state"] == JOB_STATE_DONE for t in trials.trials) == 50
    assert min(trials.losses()) < 2.0
