"""The jitted annealing path: battery thresholds, draw validity on
conditional/quantized spaces, determinism (same contract as tpe_jax)."""

import numpy as np
import pytest

from hyperopt_tpu import Domain, Trials, anneal_jax, fmin, hp
from hyperopt_tpu.base import JOB_STATE_DONE
from hyperopt_tpu.models.synthetic import DOMAINS

from test_domains import THRESHOLD_DOMAINS, median5


@pytest.mark.slow
@pytest.mark.parametrize("name", THRESHOLD_DOMAINS)
def test_anneal_jax_hits_thresholds(name):
    domain = DOMAINS[name]
    n_evals, threshold = next(iter(domain.targets.items()))
    med = median5(domain, anneal_jax.suggest, n_evals)
    assert med <= threshold, f"anneal_jax on {name}: median5 {med} > {threshold}"


def _mixed_space():
    return {
        "x": hp.uniform("x", -3.0, 7.0),
        "lr": hp.loguniform("lr", np.log(1e-4), np.log(1.0)),
        "n": hp.quniform("n", 1, 64, 1),
        "arch": hp.choice(
            "arch",
            [
                {"k": 0, "depth": hp.randint("depth", 2, 8)},
                {"k": 1, "w": hp.uniform("w", 0.0, 1.0)},
            ],
        ),
    }


def _seeded_trials(domain, n, seed=0):
    from hyperopt_tpu import rand

    trials = Trials()
    rng = np.random.default_rng(seed)
    ids = trials.new_trial_ids(n)
    docs = rand.suggest(ids, domain, trials, seed=seed)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float(rng.uniform(0, 10))}
    trials.insert_trial_docs(docs)
    trials.refresh()
    return trials


def test_anneal_jax_draw_validity():
    """Draws respect bounds, the q-grid, int types, and conditional
    activity (a trial carries vals only for its active branch)."""

    def fn(cfg):
        return cfg["x"] ** 2

    domain = Domain(fn, _mixed_space())
    trials = _seeded_trials(domain, 40)

    ids = list(range(1000, 1064))
    docs = anneal_jax.suggest(ids, domain, trials, seed=7)
    assert len(docs) == len(ids)
    for doc in docs:
        vals = doc["misc"]["vals"]
        (x,) = vals["x"]
        assert -3.0 <= x <= 7.0
        (lr,) = vals["lr"]
        assert 1e-4 * (1 - 1e-5) <= lr <= 1.0 * (1 + 1e-5)
        (n,) = vals["n"]
        assert n == round(n) and 1 <= n <= 64
        (arm,) = vals["arch"]
        assert arm in (0, 1)
        if arm == 0:
            (depth,) = vals["depth"]
            assert isinstance(depth, int) and 2 <= depth < 8
            assert vals["w"] == []
        else:
            (w,) = vals["w"]
            assert 0.0 <= w <= 1.0
            assert vals["depth"] == []


def test_anneal_jax_speculative(monkeypatch):
    """speculative=k: one dense draw serves k sequential asks; a new
    completed observation past max_stale invalidates (the anchor
    distribution depends on the history, unlike rand's prior)."""
    from functools import partial

    from hyperopt_tpu import anneal_jax, rand
    from hyperopt_tpu.base import Domain, JOB_STATE_DONE, Trials
    from hyperopt_tpu import hp

    space = {"x": hp.uniform("x", -5.0, 5.0)}
    domain = Domain(lambda x: (x - 1.0) ** 2, space)
    trials = Trials()
    docs = rand.suggest(trials.new_trial_ids(10), domain, trials, seed=0)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float(doc["tid"])}
    trials.insert_trial_docs(docs)
    trials.refresh()

    calls = []
    real_draw = anneal_jax._dense_draw

    def counting(*a):
        calls.append(a[3])
        return real_draw(*a)

    monkeypatch.setattr(anneal_jax, "_dense_draw", counting)
    algo = partial(anneal_jax.suggest, speculative=4, max_stale=0)
    out = []
    for i in range(2):  # consume only HALF the cache...
        (d,) = algo(trials.new_trial_ids(1), domain, trials, seed=50 + i)
        out.append(d["misc"]["vals"]["x"][0])
    assert calls == [4]  # one draw serves the follow-up ask
    assert len(set(out)) == 2
    # ...then a new completed observation > max_stale=0 invalidates the
    # cache EVEN THOUGH two unserved columns remain (the anchor
    # distribution depends on the history)
    new = rand.suggest(trials.new_trial_ids(1), domain, trials, seed=1)
    new[0]["state"] = JOB_STATE_DONE
    new[0]["result"] = {"status": "ok", "loss": 0.5}
    trials.insert_trial_docs(new)
    trials.refresh()
    algo(trials.new_trial_ids(1), domain, trials, seed=60)
    assert calls == [4, 4]


def test_anneal_jax_deterministic():
    def fn(cfg):
        return cfg["x"] ** 2

    domain = Domain(fn, _mixed_space())
    trials = _seeded_trials(domain, 30)
    a = anneal_jax.suggest([500, 501, 502], domain, trials, seed=11)
    b = anneal_jax.suggest([500, 501, 502], domain, trials, seed=11)
    assert [d["misc"]["vals"] for d in a] == [d["misc"]["vals"] for d in b]


def test_anneal_jax_empty_history_uses_prior():
    def fn(cfg):
        return cfg["x"] ** 2

    domain = Domain(fn, _mixed_space())
    docs = anneal_jax.suggest([1, 2, 3, 4], domain, Trials(), seed=3)
    assert len(docs) == 4
    xs = [d["misc"]["vals"]["x"][0] for d in docs]
    assert len(set(xs)) > 1  # actually random, not constant


def test_anneal_jax_concentrates_near_best():
    """With a long history whose best sits at x*=2, late draws cluster
    around it much tighter than the prior range."""

    def fn(cfg):
        return (cfg["x"] - 2.0) ** 2

    space = {"x": hp.uniform("x", -10.0, 10.0)}
    domain = Domain(fn, space)
    from hyperopt_tpu import rand

    trials = Trials()
    ids = trials.new_trial_ids(200)
    docs = rand.suggest(ids, domain, trials, seed=0)
    for doc in docs:
        (x,) = doc["misc"]["vals"]["x"]
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float((x - 2.0) ** 2)}
    trials.insert_trial_docs(docs)
    trials.refresh()

    new = anneal_jax.suggest(list(range(10_000, 10_128)), domain, trials, seed=5)
    xs = np.array([d["misc"]["vals"]["x"][0] for d in new])
    # frac = 1/(1+200*0.1) ~ 1/21 -> width ~ 1; anchors near 2
    assert np.mean(np.abs(xs - 2.0) < 1.5) > 0.8, xs
