"""The jitted annealing path: battery thresholds, draw validity on
conditional/quantized spaces, determinism (same contract as tpe_jax)."""

import numpy as np
import pytest

from hyperopt_tpu import Domain, Trials, anneal_jax, fmin, hp
from hyperopt_tpu.base import JOB_STATE_DONE
from hyperopt_tpu.models.synthetic import DOMAINS

from test_domains import THRESHOLD_DOMAINS, median5


@pytest.mark.parametrize("name", THRESHOLD_DOMAINS)
def test_anneal_jax_hits_thresholds(name):
    domain = DOMAINS[name]
    n_evals, threshold = next(iter(domain.targets.items()))
    med = median5(domain, anneal_jax.suggest, n_evals)
    assert med <= threshold, f"anneal_jax on {name}: median5 {med} > {threshold}"


def _mixed_space():
    return {
        "x": hp.uniform("x", -3.0, 7.0),
        "lr": hp.loguniform("lr", np.log(1e-4), np.log(1.0)),
        "n": hp.quniform("n", 1, 64, 1),
        "arch": hp.choice(
            "arch",
            [
                {"k": 0, "depth": hp.randint("depth", 2, 8)},
                {"k": 1, "w": hp.uniform("w", 0.0, 1.0)},
            ],
        ),
    }


def _seeded_trials(domain, n, seed=0):
    from hyperopt_tpu import rand

    trials = Trials()
    rng = np.random.default_rng(seed)
    ids = trials.new_trial_ids(n)
    docs = rand.suggest(ids, domain, trials, seed=seed)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float(rng.uniform(0, 10))}
    trials.insert_trial_docs(docs)
    trials.refresh()
    return trials


def test_anneal_jax_draw_validity():
    """Draws respect bounds, the q-grid, int types, and conditional
    activity (a trial carries vals only for its active branch)."""

    def fn(cfg):
        return cfg["x"] ** 2

    domain = Domain(fn, _mixed_space())
    trials = _seeded_trials(domain, 40)

    ids = list(range(1000, 1064))
    docs = anneal_jax.suggest(ids, domain, trials, seed=7)
    assert len(docs) == len(ids)
    for doc in docs:
        vals = doc["misc"]["vals"]
        (x,) = vals["x"]
        assert -3.0 <= x <= 7.0
        (lr,) = vals["lr"]
        assert 1e-4 * (1 - 1e-5) <= lr <= 1.0 * (1 + 1e-5)
        (n,) = vals["n"]
        assert n == round(n) and 1 <= n <= 64
        (arm,) = vals["arch"]
        assert arm in (0, 1)
        if arm == 0:
            (depth,) = vals["depth"]
            assert isinstance(depth, int) and 2 <= depth < 8
            assert vals["w"] == []
        else:
            (w,) = vals["w"]
            assert 0.0 <= w <= 1.0
            assert vals["depth"] == []


def test_anneal_jax_deterministic():
    def fn(cfg):
        return cfg["x"] ** 2

    domain = Domain(fn, _mixed_space())
    trials = _seeded_trials(domain, 30)
    a = anneal_jax.suggest([500, 501, 502], domain, trials, seed=11)
    b = anneal_jax.suggest([500, 501, 502], domain, trials, seed=11)
    assert [d["misc"]["vals"] for d in a] == [d["misc"]["vals"] for d in b]


def test_anneal_jax_empty_history_uses_prior():
    def fn(cfg):
        return cfg["x"] ** 2

    domain = Domain(fn, _mixed_space())
    docs = anneal_jax.suggest([1, 2, 3, 4], domain, Trials(), seed=3)
    assert len(docs) == 4
    xs = [d["misc"]["vals"]["x"][0] for d in docs]
    assert len(set(xs)) > 1  # actually random, not constant


def test_anneal_jax_concentrates_near_best():
    """With a long history whose best sits at x*=2, late draws cluster
    around it much tighter than the prior range."""

    def fn(cfg):
        return (cfg["x"] - 2.0) ** 2

    space = {"x": hp.uniform("x", -10.0, 10.0)}
    domain = Domain(fn, space)
    from hyperopt_tpu import rand

    trials = Trials()
    ids = trials.new_trial_ids(200)
    docs = rand.suggest(ids, domain, trials, seed=0)
    for doc in docs:
        (x,) = doc["misc"]["vals"]["x"]
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float((x - 2.0) ** 2)}
    trials.insert_trial_docs(docs)
    trials.refresh()

    new = anneal_jax.suggest(list(range(10_000, 10_128)), domain, trials, seed=5)
    xs = np.array([d["misc"]["vals"]["x"][0] for d in new])
    # frac = 1/(1+200*0.1) ~ 1/21 -> width ~ 1; anchors near 2
    assert np.mean(np.abs(xs - 2.0) < 1.5) > 0.8, xs
