"""In-memory doubles for pymongo/gridfs and pyspark.

The reference tests its Mongo backend against a real temporary ``mongod``
(SURVEY.md SS4); this image has neither mongod nor pymongo, so these
doubles implement exactly the slice of the client APIs that
``hyperopt_tpu.distributed.mongo`` / ``spark`` call -- enough to execute
the real protocol code (CAS reservation via ``find_one_and_update`` with
sort, ``update_many`` reaping, GridFS attachment put/find_one/delete,
1-task-job dispatch with job-group cancellation) end to end in-process.

They are test equipment, not features: install via
:func:`install_fake_mongo` / :func:`install_fake_spark` (monkeypatch
scoped), which drop module objects into ``sys.modules`` so the gated
``import pymongo`` / ``import pyspark`` in the backend modules succeed.
"""

from __future__ import annotations

import copy
import itertools
import sys
import threading
import types

# ---------------------------------------------------------------------------
# pymongo double
# ---------------------------------------------------------------------------


class InsertOneResult:
    def __init__(self, inserted_id):
        self.inserted_id = inserted_id


class UpdateResult:
    def __init__(self, matched_count, modified_count):
        self.matched_count = matched_count
        self.modified_count = modified_count


class DeleteResult:
    def __init__(self, deleted_count):
        self.deleted_count = deleted_count


def _get_path(doc, key):
    """Dotted-path lookup; returns (value, present)."""
    cur = doc
    for part in key.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None, False
        cur = cur[part]
    return cur, True


def _set_path(doc, key, value):
    parts = key.split(".")
    cur = doc
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
    cur[parts[-1]] = value


def _unset_path(doc, key):
    parts = key.split(".")
    cur = doc
    for p in parts[:-1]:
        if not isinstance(cur, dict) or p not in cur:
            return
        cur = cur[p]
    if isinstance(cur, dict):
        cur.pop(parts[-1], None)


def _match(doc, query):
    for k, cond in (query or {}).items():
        val, present = _get_path(doc, k)
        if isinstance(cond, dict) and any(
            isinstance(op, str) and op.startswith("$") for op in cond
        ):
            for op, operand in cond.items():
                if op == "$exists":
                    if present != bool(operand):
                        return False
                elif op == "$ne":
                    if (val if present else None) == operand:
                        return False
                elif op == "$in":
                    if not present or val not in operand:
                        return False
                elif op == "$nin":
                    if present and val in operand:
                        return False
                elif op == "$type":
                    # the slice the backends use: numeric-vs-string tids
                    types = {
                        "number": (int, float),
                        "int": int,
                        "string": str,
                    }[operand]
                    if not present or isinstance(val, bool) or (
                        not isinstance(val, types)
                    ):
                        return False
                elif op in ("$lt", "$gt", "$lte", "$gte"):
                    # mongo comparison semantics: a missing/None field
                    # never satisfies a range operator
                    if not present or val is None:
                        return False
                    ok = {
                        "$lt": val < operand,
                        "$gt": val > operand,
                        "$lte": val <= operand,
                        "$gte": val >= operand,
                    }[op]
                    if not ok:
                        return False
                else:
                    raise NotImplementedError(f"query operator {op}")
        else:
            if (val if present else None) != cond:
                return False
    return True


class Collection:
    """The jobs-collection surface MongoJobs uses, with CAS atomicity
    provided by a collection-level lock (mongod's document-level
    atomicity, conservatively)."""

    def __init__(self, name):
        self.name = name
        self._docs = []
        self._lock = threading.RLock()
        self._ids = itertools.count(1)

    # -- writes -------------------------------------------------------------
    def insert_one(self, doc):
        with self._lock:
            stored = copy.deepcopy(doc)
            if "_id" not in stored:
                stored["_id"] = next(self._ids)
            doc["_id"] = stored["_id"]  # pymongo mutates the caller's doc
            self._docs.append(stored)
            return InsertOneResult(stored["_id"])

    @staticmethod
    def _apply_update(doc, update):
        for op, fields in update.items():
            if op == "$set":
                for k, v in fields.items():
                    _set_path(doc, k, copy.deepcopy(v))
            elif op == "$unset":
                for k in fields:
                    _unset_path(doc, k)
            elif op == "$inc":
                for k, v in fields.items():
                    cur, present = _get_path(doc, k)
                    _set_path(doc, k, (cur if present and cur else 0) + v)
            else:
                raise NotImplementedError(f"update operator {op}")

    def find_one_and_update(self, filter, update, sort=None,
                            return_document=False):
        """The reservation CAS: match+sort+update one doc atomically."""
        with self._lock:
            matches = self._sorted(
                [d for d in self._docs if _match(d, filter)], sort
            )
            if not matches:
                return None
            target = matches[0]
            before = copy.deepcopy(target)
            self._apply_update(target, update)
            return copy.deepcopy(target) if return_document else before

    def update_one(self, filter, update):
        with self._lock:
            for d in self._docs:
                if _match(d, filter):
                    self._apply_update(d, update)
                    return UpdateResult(1, 1)
            return UpdateResult(0, 0)

    def update_many(self, filter, update):
        with self._lock:
            n = 0
            for d in self._docs:
                if _match(d, filter):
                    self._apply_update(d, update)
                    n += 1
            return UpdateResult(n, n)

    def delete_many(self, filter):
        with self._lock:
            keep = [d for d in self._docs if not _match(d, filter)]
            n = len(self._docs) - len(keep)
            self._docs[:] = keep
            return DeleteResult(n)

    # -- reads --------------------------------------------------------------
    @staticmethod
    def _sorted(docs, sort):
        out = list(docs)
        for key, direction in reversed(sort or []):
            out.sort(key=lambda d: _get_path(d, key)[0], reverse=direction < 0)
        return out

    def find(self, filter=None, projection=None, sort=None):
        # projection sits in pymongo's positional slot between filter
        # and sort -- modeling it (include-style only) keeps callers
        # that pass find(filter, {"field": 1}) from silently binding a
        # projection dict to sort
        with self._lock:
            docs = [
                copy.deepcopy(d)
                for d in self._sorted(
                    (d for d in self._docs if _match(d, filter)), sort
                )
            ]
        if projection:
            keep = {k for k, v in projection.items() if v} | {"_id"}
            docs = [{k: d[k] for k in keep if k in d} for d in docs]
        return docs

    def find_one(self, filter=None, sort=None):
        res = self.find(filter, sort=sort)
        return res[0] if res else None


class Database:
    def __init__(self, name):
        self.name = name
        self._collections = {}
        self._gridfs = {}  # collection-prefix -> {file_id: (filename, bytes)}
        self._lock = threading.RLock()

    def __getitem__(self, name):
        with self._lock:
            coll = self._collections.get(name)
            if coll is None:
                coll = self._collections[name] = Collection(name)
            return coll


class MongoClient:
    """Same connection string -> same server state (class-level registry),
    so driver and worker 'connections' share one database like a real
    mongod."""

    _registry = {}
    _registry_lock = threading.RLock()

    def __init__(self, conn_str="mongodb://localhost:27017"):
        with MongoClient._registry_lock:
            dbs = MongoClient._registry.get(conn_str)
            if dbs is None:
                dbs = MongoClient._registry[conn_str] = {}
            self._dbs = dbs

    def __getitem__(self, dbname):
        with MongoClient._registry_lock:
            db = self._dbs.get(dbname)
            if db is None:
                db = self._dbs[dbname] = Database(dbname)
            return db


class _GridOut:
    def __init__(self, file_id, data):
        self._id = file_id
        self._data = data

    def read(self):
        return self._data


class GridFS:
    """put / find_one({'filename': ...}) / delete -- the attachment slice."""

    _ids = itertools.count(1)

    def __init__(self, db, collection="fs"):
        with db._lock:
            self._files = db._gridfs.setdefault(collection, {})
        self._lock = db._lock

    def put(self, data, filename=None, **kw):
        if isinstance(data, str):
            data = data.encode()
        with self._lock:
            file_id = next(GridFS._ids)
            self._files[file_id] = (filename, bytes(data))
            return file_id

    def find_one(self, query):
        filename = query["filename"]
        with self._lock:
            for file_id in sorted(self._files, reverse=True):
                fn, data = self._files[file_id]
                if fn == filename:
                    return _GridOut(file_id, data)
        return None

    def find(self, query):
        filename = query["filename"]
        with self._lock:
            return [
                _GridOut(file_id, data)
                for file_id in sorted(self._files)
                for fn, data in [self._files[file_id]]
                if fn == filename
            ]

    def get_last_version(self, filename):
        obj = self.find_one({"filename": filename})
        if obj is None:
            raise KeyError(filename)  # stands in for gridfs.NoFile
        return obj

    def delete(self, file_id):
        with self._lock:
            self._files.pop(file_id, None)


def install_fake_mongo(monkeypatch):
    """sys.modules['pymongo'|'gridfs'] -> these doubles; registry reset.

    The installed client dispatches: ``mongodb://file:/abs/dir``
    connection strings get the cross-process file-backed server,
    everything else the in-memory registry double."""
    pymongo_mod = types.ModuleType("pymongo")
    pymongo_mod.MongoClient = _DispatchMongoClient
    gridfs_mod = types.ModuleType("gridfs")
    gridfs_mod.GridFS = _DispatchGridFS
    monkeypatch.setitem(sys.modules, "pymongo", pymongo_mod)
    monkeypatch.setitem(sys.modules, "gridfs", gridfs_mod)
    MongoClient._registry.clear()
    return pymongo_mod


# ---------------------------------------------------------------------------
# FILE-BACKED pymongo double: one "server" shared across PROCESSES
# ---------------------------------------------------------------------------
#
# The in-memory double above proves CAS exclusivity only across threads
# (its lock is a threading.RLock).  This variant persists each database
# to a pickle file guarded by an O_EXCL lock file, so separate worker
# PROCESSES -- spawned the way the reference spawns
# ``hyperopt-mongo-worker`` subprocesses against a temp mongod -- contend
# through the filesystem exactly like clients of one server.  Connection
# strings of the form ``mongodb://file:/abs/dir`` select it.


class _FileLock:
    """O_CREAT|O_EXCL lock file: the only cross-process mutual exclusion
    primitive that needs nothing but a shared filesystem."""

    def __init__(self, path, timeout=30.0):
        self.path = path + ".lock"
        self.timeout = timeout

    def __enter__(self):
        import os
        import time as _time

        deadline = _time.monotonic() + self.timeout
        while True:
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                return self
            except FileExistsError:
                if _time.monotonic() > deadline:
                    raise TimeoutError(f"lock {self.path} not released")
                _time.sleep(0.002)

    def __exit__(self, *exc):
        import os

        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


class FileCollection:
    """Same surface as :class:`Collection`, state in ``<dir>/<name>.pkl``;
    every operation is load -> mutate -> atomic-replace under the lock."""

    def __init__(self, dirpath, name):
        import os

        os.makedirs(dirpath, exist_ok=True)
        self.name = name
        self._path = os.path.join(dirpath, name + ".pkl")

    def _load(self):
        import pickle

        try:
            with open(self._path, "rb") as f:
                return pickle.load(f)
        except FileNotFoundError:
            return {"docs": [], "next_id": 1}

    def _store(self, state):
        import os
        import pickle

        tmp = f"{self._path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, self._path)

    # -- writes -------------------------------------------------------------
    def insert_one(self, doc):
        with _FileLock(self._path):
            state = self._load()
            stored = copy.deepcopy(doc)
            if "_id" not in stored:
                stored["_id"] = state["next_id"]
                state["next_id"] += 1
            doc["_id"] = stored["_id"]
            state["docs"].append(stored)
            self._store(state)
            return InsertOneResult(stored["_id"])

    def find_one_and_update(self, filter, update, sort=None,
                            return_document=False):
        with _FileLock(self._path):
            state = self._load()
            matches = Collection._sorted(
                [d for d in state["docs"] if _match(d, filter)], sort
            )
            if not matches:
                return None
            target = matches[0]
            before = copy.deepcopy(target)
            Collection._apply_update(target, update)
            self._store(state)
            return copy.deepcopy(target) if return_document else before

    def update_one(self, filter, update):
        with _FileLock(self._path):
            state = self._load()
            for d in state["docs"]:
                if _match(d, filter):
                    Collection._apply_update(d, update)
                    self._store(state)
                    return UpdateResult(1, 1)
            return UpdateResult(0, 0)

    def update_many(self, filter, update):
        with _FileLock(self._path):
            state = self._load()
            n = 0
            for d in state["docs"]:
                if _match(d, filter):
                    Collection._apply_update(d, update)
                    n += 1
            if n:
                self._store(state)
            return UpdateResult(n, n)

    def delete_many(self, filter):
        with _FileLock(self._path):
            state = self._load()
            keep = [d for d in state["docs"] if not _match(d, filter)]
            n = len(state["docs"]) - len(keep)
            state["docs"] = keep
            self._store(state)
            return DeleteResult(n)

    # -- reads --------------------------------------------------------------
    def find(self, filter=None, projection=None, sort=None):
        with _FileLock(self._path):
            docs = self._load()["docs"]
        out = [
            copy.deepcopy(d)
            for d in Collection._sorted(
                (d for d in docs if _match(d, filter)), sort
            )
        ]
        if projection:
            keep = {k for k, v in projection.items() if v} | {"_id"}
            out = [{k: d[k] for k in keep if k in d} for d in out]
        return out

    def find_one(self, filter=None, sort=None):
        res = self.find(filter, sort=sort)
        return res[0] if res else None


class FileDatabase:
    def __init__(self, dirpath, name):
        import os

        self.name = name
        self._dir = os.path.join(dirpath, name)
        self._gridfs_dir = os.path.join(self._dir, "_gridfs")

    def __getitem__(self, name):
        return FileCollection(self._dir, name)


class FileGridFS:
    """File-backed GridFS slice (put / find_one by filename / delete)."""

    def __init__(self, db, collection="fs"):
        import os

        self._dir = os.path.join(db._gridfs_dir, collection)
        os.makedirs(self._dir, exist_ok=True)
        self._state = os.path.join(self._dir, "files.pkl")

    def _load(self):
        import pickle

        try:
            with open(self._state, "rb") as f:
                return pickle.load(f)
        except FileNotFoundError:
            return {"files": {}, "next_id": 1}

    def _store(self, state):
        import os
        import pickle

        tmp = f"{self._state}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, self._state)

    def put(self, data, filename=None, **kw):
        if isinstance(data, str):
            data = data.encode()
        with _FileLock(self._state):
            state = self._load()
            file_id = state["next_id"]
            state["next_id"] += 1
            state["files"][file_id] = (filename, bytes(data))
            self._store(state)
            return file_id

    def find_one(self, query):
        filename = query["filename"]
        with _FileLock(self._state):
            files = self._load()["files"]
        for file_id in sorted(files, reverse=True):
            fn, data = files[file_id]
            if fn == filename:
                return _GridOut(file_id, data)
        return None

    def find(self, query):
        filename = query["filename"]
        with _FileLock(self._state):
            files = self._load()["files"]
        return [
            _GridOut(file_id, data)
            for file_id in sorted(files)
            for fn, data in [files[file_id]]
            if fn == filename
        ]

    def get_last_version(self, filename):
        obj = self.find_one({"filename": filename})
        if obj is None:
            raise KeyError(filename)  # stands in for gridfs.NoFile
        return obj

    def delete(self, file_id):
        with _FileLock(self._state):
            state = self._load()
            state["files"].pop(file_id, None)
            self._store(state)


class FileMongoClient:
    """``MongoClient('mongodb://file:/abs/dir')`` -> file-backed server."""

    def __init__(self, conn_str):
        path = conn_str
        for prefix in ("mongodb://file:", "file:"):
            if path.startswith(prefix):
                path = path[len(prefix):]
                break
        self._dir = path

    def __getitem__(self, dbname):
        return FileDatabase(self._dir, dbname)


class _DispatchMongoClient:
    """Route ``file:`` connection strings to the file-backed server,
    everything else to the in-memory registry double."""

    def __new__(cls, conn_str="mongodb://localhost:27017"):
        if "file:" in conn_str:
            return FileMongoClient(conn_str)
        return MongoClient(conn_str)


class _DispatchGridFS:
    def __new__(cls, db, collection="fs"):
        if isinstance(db, FileDatabase):
            return FileGridFS(db, collection)
        return GridFS(db, collection)


def install_fake_mongo_modules():
    """monkeypatch-free installer (for subprocess bootstrap): drop the
    dispatching doubles into ``sys.modules`` permanently."""
    pymongo_mod = types.ModuleType("pymongo")
    pymongo_mod.MongoClient = _DispatchMongoClient
    gridfs_mod = types.ModuleType("gridfs")
    gridfs_mod.GridFS = _DispatchGridFS
    sys.modules["pymongo"] = pymongo_mod
    sys.modules["gridfs"] = gridfs_mod
    return pymongo_mod


# ---------------------------------------------------------------------------
# pyspark double
# ---------------------------------------------------------------------------


class _FakeRDD:
    def __init__(self, sc, data, group, fn=None):
        self._sc = sc
        self._data = data
        self._group = group
        self._fn = fn

    def map(self, f):
        return _FakeRDD(self._sc, self._data, self._group, f)

    def collect(self):
        def check():
            if self._group is not None and self._group in self._sc._cancelled:
                raise RuntimeError(f"job group {self._group} cancelled")

        check()
        out = []
        for x in self._data:
            out.append(self._fn(x) if self._fn else x)
            # Spark cancels at task boundaries; a group cancelled while the
            # task ran surfaces as a failed collect
            check()
        return out


class FakeSparkContext:
    """Thread-local job groups + cancellable collects, like SparkContext."""

    def __init__(self, default_parallelism=2):
        self.defaultParallelism = default_parallelism
        self._local = threading.local()
        self._cancelled = set()
        self.cancel_calls = []
        self.parallelize_calls = 0
        self._lock = threading.Lock()

    def setJobGroup(self, group, description, interruptOnCancel=False):
        self._local.group = group

    def cancelJobGroup(self, group):
        with self._lock:
            self._cancelled.add(group)
            self.cancel_calls.append(group)

    def parallelize(self, data, numSlices=None):
        with self._lock:
            self.parallelize_calls += 1
        return _FakeRDD(self, list(data), getattr(self._local, "group", None))


class FakeSparkSession:
    def __init__(self, default_parallelism=2):
        self.sparkContext = FakeSparkContext(default_parallelism)


class _Builder:
    def getOrCreate(self):
        return FakeSparkSession()


def install_fake_spark(monkeypatch):
    """sys.modules['pyspark'|'pyspark.sql'] -> doubles; returns the module."""
    pyspark_mod = types.ModuleType("pyspark")
    sql_mod = types.ModuleType("pyspark.sql")

    class SparkSession(FakeSparkSession):
        builder = _Builder()

    sql_mod.SparkSession = SparkSession
    pyspark_mod.sql = sql_mod
    monkeypatch.setitem(sys.modules, "pyspark", pyspark_mod)
    monkeypatch.setitem(sys.modules, "pyspark.sql", sql_mod)
    return pyspark_mod
