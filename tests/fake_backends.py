"""In-memory doubles for pymongo/gridfs and pyspark.

The reference tests its Mongo backend against a real temporary ``mongod``
(SURVEY.md SS4); this image has neither mongod nor pymongo, so these
doubles implement exactly the slice of the client APIs that
``hyperopt_tpu.distributed.mongo`` / ``spark`` call -- enough to execute
the real protocol code (CAS reservation via ``find_one_and_update`` with
sort, ``update_many`` reaping, GridFS attachment put/find_one/delete,
1-task-job dispatch with job-group cancellation) end to end in-process.

They are test equipment, not features: install via
:func:`install_fake_mongo` / :func:`install_fake_spark` (monkeypatch
scoped), which drop module objects into ``sys.modules`` so the gated
``import pymongo`` / ``import pyspark`` in the backend modules succeed.
"""

from __future__ import annotations

import copy
import itertools
import sys
import threading
import types

# ---------------------------------------------------------------------------
# pymongo double
# ---------------------------------------------------------------------------


class InsertOneResult:
    def __init__(self, inserted_id):
        self.inserted_id = inserted_id


class UpdateResult:
    def __init__(self, matched_count, modified_count):
        self.matched_count = matched_count
        self.modified_count = modified_count


class DeleteResult:
    def __init__(self, deleted_count):
        self.deleted_count = deleted_count


def _get_path(doc, key):
    """Dotted-path lookup; returns (value, present)."""
    cur = doc
    for part in key.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None, False
        cur = cur[part]
    return cur, True


def _set_path(doc, key, value):
    parts = key.split(".")
    cur = doc
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
    cur[parts[-1]] = value


def _match(doc, query):
    for k, cond in (query or {}).items():
        val, present = _get_path(doc, k)
        if isinstance(cond, dict) and any(
            isinstance(op, str) and op.startswith("$") for op in cond
        ):
            for op, operand in cond.items():
                if op == "$lt":
                    if not present or val is None or not (val < operand):
                        return False
                elif op == "$gt":
                    if not present or val is None or not (val > operand):
                        return False
                else:
                    raise NotImplementedError(f"query operator {op}")
        else:
            if (val if present else None) != cond:
                return False
    return True


class Collection:
    """The jobs-collection surface MongoJobs uses, with CAS atomicity
    provided by a collection-level lock (mongod's document-level
    atomicity, conservatively)."""

    def __init__(self, name):
        self.name = name
        self._docs = []
        self._lock = threading.RLock()
        self._ids = itertools.count(1)

    # -- writes -------------------------------------------------------------
    def insert_one(self, doc):
        with self._lock:
            stored = copy.deepcopy(doc)
            if "_id" not in stored:
                stored["_id"] = next(self._ids)
            doc["_id"] = stored["_id"]  # pymongo mutates the caller's doc
            self._docs.append(stored)
            return InsertOneResult(stored["_id"])

    @staticmethod
    def _apply_update(doc, update):
        for op, fields in update.items():
            if op != "$set":
                raise NotImplementedError(f"update operator {op}")
            for k, v in fields.items():
                _set_path(doc, k, copy.deepcopy(v))

    def find_one_and_update(self, filter, update, sort=None,
                            return_document=False):
        """The reservation CAS: match+sort+update one doc atomically."""
        with self._lock:
            matches = self._sorted(
                [d for d in self._docs if _match(d, filter)], sort
            )
            if not matches:
                return None
            target = matches[0]
            before = copy.deepcopy(target)
            self._apply_update(target, update)
            return copy.deepcopy(target) if return_document else before

    def update_one(self, filter, update):
        with self._lock:
            for d in self._docs:
                if _match(d, filter):
                    self._apply_update(d, update)
                    return UpdateResult(1, 1)
            return UpdateResult(0, 0)

    def update_many(self, filter, update):
        with self._lock:
            n = 0
            for d in self._docs:
                if _match(d, filter):
                    self._apply_update(d, update)
                    n += 1
            return UpdateResult(n, n)

    def delete_many(self, filter):
        with self._lock:
            keep = [d for d in self._docs if not _match(d, filter)]
            n = len(self._docs) - len(keep)
            self._docs[:] = keep
            return DeleteResult(n)

    # -- reads --------------------------------------------------------------
    @staticmethod
    def _sorted(docs, sort):
        out = list(docs)
        for key, direction in reversed(sort or []):
            out.sort(key=lambda d: _get_path(d, key)[0], reverse=direction < 0)
        return out

    def find(self, filter=None, sort=None):
        with self._lock:
            return [
                copy.deepcopy(d)
                for d in self._sorted(
                    (d for d in self._docs if _match(d, filter)), sort
                )
            ]

    def find_one(self, filter=None, sort=None):
        res = self.find(filter, sort=sort)
        return res[0] if res else None


class Database:
    def __init__(self, name):
        self.name = name
        self._collections = {}
        self._gridfs = {}  # collection-prefix -> {file_id: (filename, bytes)}
        self._lock = threading.RLock()

    def __getitem__(self, name):
        with self._lock:
            coll = self._collections.get(name)
            if coll is None:
                coll = self._collections[name] = Collection(name)
            return coll


class MongoClient:
    """Same connection string -> same server state (class-level registry),
    so driver and worker 'connections' share one database like a real
    mongod."""

    _registry = {}
    _registry_lock = threading.RLock()

    def __init__(self, conn_str="mongodb://localhost:27017"):
        with MongoClient._registry_lock:
            dbs = MongoClient._registry.get(conn_str)
            if dbs is None:
                dbs = MongoClient._registry[conn_str] = {}
            self._dbs = dbs

    def __getitem__(self, dbname):
        with MongoClient._registry_lock:
            db = self._dbs.get(dbname)
            if db is None:
                db = self._dbs[dbname] = Database(dbname)
            return db


class _GridOut:
    def __init__(self, file_id, data):
        self._id = file_id
        self._data = data

    def read(self):
        return self._data


class GridFS:
    """put / find_one({'filename': ...}) / delete -- the attachment slice."""

    _ids = itertools.count(1)

    def __init__(self, db, collection="fs"):
        with db._lock:
            self._files = db._gridfs.setdefault(collection, {})
        self._lock = db._lock

    def put(self, data, filename=None, **kw):
        if isinstance(data, str):
            data = data.encode()
        with self._lock:
            file_id = next(GridFS._ids)
            self._files[file_id] = (filename, bytes(data))
            return file_id

    def find_one(self, query):
        filename = query["filename"]
        with self._lock:
            for file_id in sorted(self._files, reverse=True):
                fn, data = self._files[file_id]
                if fn == filename:
                    return _GridOut(file_id, data)
        return None

    def delete(self, file_id):
        with self._lock:
            self._files.pop(file_id, None)


def install_fake_mongo(monkeypatch):
    """sys.modules['pymongo'|'gridfs'] -> these doubles; registry reset."""
    pymongo_mod = types.ModuleType("pymongo")
    pymongo_mod.MongoClient = MongoClient
    gridfs_mod = types.ModuleType("gridfs")
    gridfs_mod.GridFS = GridFS
    monkeypatch.setitem(sys.modules, "pymongo", pymongo_mod)
    monkeypatch.setitem(sys.modules, "gridfs", gridfs_mod)
    MongoClient._registry.clear()
    return pymongo_mod


# ---------------------------------------------------------------------------
# pyspark double
# ---------------------------------------------------------------------------


class _FakeRDD:
    def __init__(self, sc, data, group, fn=None):
        self._sc = sc
        self._data = data
        self._group = group
        self._fn = fn

    def map(self, f):
        return _FakeRDD(self._sc, self._data, self._group, f)

    def collect(self):
        def check():
            if self._group is not None and self._group in self._sc._cancelled:
                raise RuntimeError(f"job group {self._group} cancelled")

        check()
        out = []
        for x in self._data:
            out.append(self._fn(x) if self._fn else x)
            # Spark cancels at task boundaries; a group cancelled while the
            # task ran surfaces as a failed collect
            check()
        return out


class FakeSparkContext:
    """Thread-local job groups + cancellable collects, like SparkContext."""

    def __init__(self, default_parallelism=2):
        self.defaultParallelism = default_parallelism
        self._local = threading.local()
        self._cancelled = set()
        self.cancel_calls = []
        self.parallelize_calls = 0
        self._lock = threading.Lock()

    def setJobGroup(self, group, description, interruptOnCancel=False):
        self._local.group = group

    def cancelJobGroup(self, group):
        with self._lock:
            self._cancelled.add(group)
            self.cancel_calls.append(group)

    def parallelize(self, data, numSlices=None):
        with self._lock:
            self.parallelize_calls += 1
        return _FakeRDD(self, list(data), getattr(self._local, "group", None))


class FakeSparkSession:
    def __init__(self, default_parallelism=2):
        self.sparkContext = FakeSparkContext(default_parallelism)


class _Builder:
    def getOrCreate(self):
        return FakeSparkSession()


def install_fake_spark(monkeypatch):
    """sys.modules['pyspark'|'pyspark.sql'] -> doubles; returns the module."""
    pyspark_mod = types.ModuleType("pyspark")
    sql_mod = types.ModuleType("pyspark.sql")

    class SparkSession(FakeSparkSession):
        builder = _Builder()

    sql_mod.SparkSession = SparkSession
    pyspark_mod.sql = sql_mod
    monkeypatch.setitem(sys.modules, "pyspark", pyspark_mod)
    monkeypatch.setitem(sys.modules, "pyspark.sql", sql_mod)
    return pyspark_mod
