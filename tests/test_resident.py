"""Device-resident trial history: O(1) delta tells + fused tell+ask.

The round-7 contract (ISSUE 4): the resident ObsBuffer mirror -- O(D)
delta tells applied on device instead of O(n_obs*D) re-uploads -- must
produce a suggestion stream BITWISE equal to the re-upload path, through
every regime stacked on top of it (fused one-dispatch driver with
ask-ahead, speculative k-wide draws, the saturated-categorical
auto-degrade guard, annealing/adaptive variants), across both the
device-bucket growth boundary and the host ``ObsBuffer._grow`` capacity
crossing.  Traffic and dispatch behavior is pinned by DETERMINISTIC
counters, never timing.
"""

import pickle
import warnings

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu import anneal_jax, atpe_jax, tpe_jax
from hyperopt_tpu.base import Domain
from hyperopt_tpu.fmin import FMinIter, partial
from hyperopt_tpu.jax_trials import (
    JaxTrials,
    MIN_CAPACITY,
    ObsBuffer,
    obs_buffer_for,
)
from hyperopt_tpu.ops.compile import compile_space

# a small mixed space: uniform + log + quantized + conditional branch
# with a nested uniform / randint -- every dim family the packer knows
MIXED = {
    "x": hp.uniform("x", -5, 5),
    "lr": hp.loguniform("lr", -5, 0),
    "q": hp.quniform("q", 0, 10, 1),
    "c": hp.choice("c", [
        {"k": 0, "a": hp.uniform("a", 0, 1)},
        {"k": 1, "b": hp.randint("b", 3)},
    ]),
}


def mixed_obj(cfg):
    base = (
        (cfg["x"] - 1) ** 2 / 10
        + abs(np.log(cfg["lr"]) + 2) / 3
        + abs(cfg["q"] - 4) / 5
    )
    return base + (
        cfg["c"]["a"] if cfg["c"]["k"] == 0 else 0.1 * cfg["c"]["b"]
    )


def run_stream(algo, trials, n, seed=7, obj=mixed_obj, space=MIXED):
    fmin(
        obj, space, algo=algo, max_evals=n, trials=trials,
        rstate=np.random.default_rng(seed), show_progressbar=False,
        return_argmin=False,
    )
    return [t["misc"]["vals"] for t in trials.trials]


@pytest.mark.slow
def test_resident_parity_200_sequential():
    """200 sequential trials -- past the ``_grow`` capacity crossing
    (count 128: cap 128 -> 512) AND the device-bucket growth boundary
    (count 129: bucket 128 -> 256) -- with the resident-delta and the
    fused ask-ahead streams bitwise equal to the re-upload stream."""
    kw = dict(n_EI_candidates=16)
    base = run_stream(partial(tpe_jax.suggest, **kw), Trials(), 200)
    resident = run_stream(
        partial(tpe_jax.suggest, resident=True, **kw), Trials(), 200
    )
    fused = run_stream(
        partial(tpe_jax.suggest, fused=True, **kw),
        JaxTrials(resident=True), 200,
    )
    assert len(base) == 200
    assert base == resident
    assert base == fused


def test_resident_parity_short():
    """Fast-tier twin of the 200-trial pin: 60 trials, all three
    regimes bitwise equal (boundary crossings covered by the slow
    test and by the buffer-level tests below)."""
    kw = dict(n_EI_candidates=16)
    base = run_stream(partial(tpe_jax.suggest, **kw), Trials(), 60)
    resident = run_stream(
        partial(tpe_jax.suggest, resident=True, **kw), Trials(), 60
    )
    fused = run_stream(
        partial(tpe_jax.suggest, fused=True, **kw),
        JaxTrials(resident=True), 60,
    )
    assert base == resident == fused


def test_speculative_parity_on_resident():
    """speculative=k keeps its exact stream on top of the resident
    state engine (the k-wide redraws ride the delta/fused dispatch)."""
    kw = dict(n_EI_candidates=16, speculative=4)
    base = run_stream(partial(tpe_jax.suggest, **kw), Trials(), 70)
    resident = run_stream(
        partial(tpe_jax.suggest, resident=True, **kw), Trials(), 70
    )
    assert base == resident


def test_saturated_guard_identical_on_resident():
    """The pure-categorical auto-degrade guard is build-time space
    logic: same one-time warning, same degraded one-dispatch-per-ask
    stream, resident or not."""
    space = {"r": hp.randint("r", 3), "s": hp.randint("s", 4)}

    def obj(cfg):
        return cfg["r"] * 0.1 + cfg["s"] * 0.01

    streams = {}
    for resident in (False, True):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            streams[resident] = run_stream(
                partial(
                    tpe_jax.suggest, speculative=4,
                    resident=True if resident else None,
                ),
                Trials(), 40, obj=obj, space=space,
            )
        assert sum("speculative=4 disabled" in str(x.message) for x in w) == 1
    assert streams[False] == streams[True]


def test_anneal_resident_parity():
    base = run_stream(anneal_jax.suggest, Trials(), 60)
    resident = run_stream(
        partial(anneal_jax.suggest, resident=True), Trials(), 60
    )
    assert base == resident


def test_atpe_resident_parity():
    base = run_stream(atpe_jax.suggest, Trials(), 50)
    resident = run_stream(
        partial(atpe_jax.suggest, resident=True), Trials(), 50
    )
    assert base == resident


def test_fused_dispatch_and_transfer_counters():
    """Deterministic accounting through the real sequential driver:
    one dispatch per trial (+ the trailing ask-ahead pre-dispatch), one
    full upload (cold mirror), every other tell an O(D) delta of
    exactly 5*D+8 bytes."""
    domain = Domain(mixed_obj, MIXED)
    trials = JaxTrials(resident=True)
    FMinIter(
        partial(tpe_jax.suggest, fused=True, n_EI_candidates=16),
        domain, trials, rstate=np.random.default_rng(3),
        max_evals=60, show_progressbar=False,
    ).exhaust()
    buf = next(iter(trials._buffers.values()))
    D = buf.space.n_dims
    # 60 asks, each one dispatch, + 1 pre-dispatch after the last result
    assert buf.dispatch_count == 61
    assert buf.full_uploads == 1
    # warm asks 21..60 fused a delta each; the trailing pre-dispatch too
    assert buf.delta_tells == 40
    bucket = buf._device_bucket()
    full_bytes = bucket * (4 * D + D + 4 + 1)
    delta_bytes = 5 * D + 8
    assert buf.transfer_bytes_total == (
        full_bytes + buf.delta_tells * delta_bytes
    )


def test_resident_delta_bytes_flat_in_history_size():
    """The per-tell upload is O(D) -- independent of the observation
    count (the acceptance contract the bench rows measure at scale)."""
    ps = compile_space(MIXED)
    per_tell = {}
    for n_obs in (40, 3 * MIN_CAPACITY):
        buf = ObsBuffer(ps, resident=True)
        for i in range(n_obs):
            buf.add({"x": float(i % 7), "q": 1.0}, float(i % 5))
        buf.device_arrays()  # settle the mirror
        b0 = buf.transfer_bytes_total
        buf.add({"x": 0.5, "q": 2.0}, 0.25)
        buf.device_arrays()
        per_tell[n_obs] = buf.transfer_bytes_total - b0
    assert per_tell[40] == per_tell[3 * MIN_CAPACITY] == 5 * ps.n_dims + 8


def test_resident_mirror_matches_host_across_regimes():
    """Buffer-level parity: the resident device view equals the
    re-upload view bitwise after in-order appends, a multi-tell
    backlog, bucket growth, capacity growth, AND an out-of-order (late
    completion) insert that forces re-materialization."""
    import jax

    ps = compile_space(MIXED)
    res = ObsBuffer(ps, resident=True)
    ref = ObsBuffer(ps)

    def check():
        a = jax.device_get(res.device_arrays())
        b = jax.device_get(ref.device_arrays())
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def add_both(vals, loss, tid=None):
        res.add(dict(vals), loss, tid=tid)
        ref.add(dict(vals), loss, tid=tid)

    # in-order appends, syncing the mirror every few tells (multi-delta
    # backlogs) and crossing both the bucket and capacity boundaries
    tid = 0
    for i in range(MIN_CAPACITY + 10):
        add_both({"x": float(i % 9), "lr": 0.1}, float(i % 4), tid=tid)
        tid += 2  # leave odd tids free for the late insert below
        if i % 3 == 0:
            check()
    check()
    assert res.capacity > MIN_CAPACITY  # _grow crossed
    assert res._device_bucket() > MIN_CAPACITY  # bucket grew

    # late completion: insert at a mid-buffer tid -> tail shift on the
    # host, full re-materialization on the device
    add_both({"x": -1.0, "lr": 0.5}, 9.9, tid=5)
    assert res._resident_full
    check()


def test_resident_buffer_pickles_without_device_state():
    ps = compile_space(MIXED)
    buf = ObsBuffer(ps, resident=True)
    for i in range(8):
        buf.add({"x": float(i)}, float(i))
    buf.device_arrays()
    clone = pickle.loads(pickle.dumps(buf))
    assert clone.resident and clone._resident is None
    # the restored buffer re-materializes and serves the same view
    import jax

    a = jax.device_get(clone.device_arrays())
    b = jax.device_get(buf.device_arrays())
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_set_resident_flips_safely_mid_run():
    """Flipping residency between asks must not change the stream (the
    host arrays stay authoritative)."""
    domain = Domain(mixed_obj, MIXED)
    trials = Trials()
    seeds = np.random.default_rng(0).integers(2**31 - 1, size=40)
    stream = []
    for i, s in enumerate(seeds):
        if i == 25:  # flip once warm, mid-run
            obs_buffer_for(domain, trials, resident=True)
        (doc,) = tpe_jax.suggest(
            trials.new_trial_ids(1), domain, trials, int(s),
            n_EI_candidates=16,
        )
        stream.append({k: list(v) for k, v in doc["misc"]["vals"].items()})
        doc["state"] = 2  # JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float(i % 7)}
        trials.insert_trial_docs([doc])
        trials.refresh()

    domain2 = Domain(mixed_obj, MIXED)
    trials2 = Trials()
    stream2 = []
    for i, s in enumerate(seeds):
        (doc,) = tpe_jax.suggest(
            trials2.new_trial_ids(1), domain2, trials2, int(s),
            n_EI_candidates=16,
        )
        stream2.append({k: list(v) for k, v in doc["misc"]["vals"].items()})
        doc["state"] = 2
        doc["result"] = {"status": "ok", "loss": float(i % 7)}
        trials2.insert_trial_docs([doc])
        trials2.refresh()
    assert stream == stream2
