"""graftmesh (ISSUE 12): shard the study and population axes across
multi-chip meshes.

The acceptance contract, pinned deterministically on the session's
8-virtual-CPU-device harness (tests/conftest.py):

* SERVE PARITY: the mesh-sharded batched tell+ask is BITWISE the
  single-device engine -- on a 1-device mesh and on a 4-virtual-device
  mesh -- through the full 64-study scenario: join/leave churn with
  slot reuse, dirty-slot re-materialization from an out-of-order tell,
  multi-tell backlog drains, and a NaN tenant quarantined with every
  sibling stream pinned (the single-device engine is itself pinned
  bitwise against solo fused runs by tests/test_serve.py, so parity
  here is transitive to the solo path);
* SHARD-LOCALITY: on a multi-device mesh, a dirty slot re-uploads only
  ITS shard (counted: ``shard_restacks``), sibling shards' device
  buffers are reused untouched;
* SLOT CAPACITY: capacities round up to a multiple of the mesh
  study-axis size -- including non-pow2 sizes -- padding dead slots
  behind the active mask (the uneven-churn regression);
* PBT / device-ASHA: the shard_map population schedules are bitwise
  the unsharded ones at equal population, with all-gathers only at
  exploit/rung boundaries.
"""

import math
import subprocess
import sys

import numpy as np
import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.exceptions import StudyPoisoned, StudyQuarantined
from hyperopt_tpu.serve import SuggestService
from hyperopt_tpu.serve.batched import slot_capacity

# a deliberately small space: the mesh suite compiles its own 64-slot
# vmapped step programs per mesh (cache-keyed by mesh), so the per-slot
# body is kept cheap to protect the fast-tier wall-clock budget
SPACE = {
    "x": hp.uniform("x", -5.0, 5.0),
    "c": hp.choice("c", [0, 1, 2]),
}
ALGO_KW = dict(n_cand=8, n_cand_cat=4)
N_STARTUP = 2


def loss_fn(vals):
    return (vals["x"] - 1.0) ** 2 / 10 + 0.1 * vals["c"]


def drive(svc, handles, streams, rounds):
    for _ in range(rounds):
        futs = [(h, h.ask_async()) for h in handles]
        svc.pump()
        for h, f in futs:
            tid, vals = f.result(timeout=60)
            streams.setdefault(h.name, []).append(vals)
            h.tell(tid, loss_fn(vals))


def serve_scenario(mesh, n_studies=60, max_batch=64):
    """The acceptance scenario: churn + out-of-order dirty slot +
    multi-tell backlog + NaN quarantine, all in one service run.
    Returns (streams, counters, quarantined)."""
    svc = SuggestService(
        SPACE, max_batch=max_batch, background=False,
        n_startup_jobs=N_STARTUP, mesh=mesh, **ALGO_KW,
    )
    streams = {}
    handles = [
        svc.create_study(f"s{i:02d}", seed=400 + i)
        for i in range(n_studies)
    ]
    drive(svc, handles, streams, 2)

    # churn: close a quarter mid-run, join replacements (slot reuse)
    for h in handles[: n_studies // 4]:
        h.close()
    survivors = handles[n_studies // 4:]
    joined = [
        svc.create_study(f"j{i:02d}", seed=600 + i)
        for i in range(n_studies // 4)
    ]
    drive(svc, survivors + joined, streams, 2)

    # dirty-slot re-materialization: an OUT-OF-ORDER tell (tid below
    # the study's last) forces the slot back to host truth
    ooo = survivors[0]
    st = svc.scheduler.study(ooo.name)
    t_hi = st.next_tid
    t_lo = t_hi + 1  # tell hi first, then lo: lo lands out of order
    ooo.tell(t_lo, 0.9, vals={"x": 0.5, "c": 1})
    ooo.tell(t_hi, 0.7, vals={"x": -0.5, "c": 0})
    st.next_tid = t_lo + 1
    assert st.dirty, "out-of-order tell must dirty the slot"

    # multi-tell backlog on another study (drains via the masked-delta
    # program, at most one staged tell fused into the next ask)
    blg = survivors[1]
    st_b = svc.scheduler.study(blg.name)
    base = st_b.next_tid
    for k in range(3):
        blg.tell(base + k, 0.5 + 0.1 * k, vals={"x": 0.1 * k, "c": 0})
    st_b.next_tid = base + 3
    drive(svc, survivors + joined, streams, 2)

    # a NaN tenant trips the finite check K times and is evicted;
    # every sibling must stay bitwise undisturbed
    bad = svc.create_study("bad", seed=999)
    st_bad = svc.scheduler.study("bad")
    bad.tell(st_bad.next_tid, float("nan"), vals={"x": 0.0, "c": 0})
    st_bad.next_tid += 1
    for _ in range(4):
        if st_bad.quarantined:
            break
        try:
            f = bad.ask_async()
            svc.pump()
            f.exception(timeout=60)
        except (StudyPoisoned, StudyQuarantined):
            break
    drive(svc, survivors + joined, streams, 1)

    counters = dict(svc.counters)
    quarantined = st_bad.quarantined
    svc.shutdown()
    return streams, counters, quarantined


_REF = {}


def _reference(n_studies=60):
    """The single-device engine's scenario run (shared across params:
    the suite compares every mesh against ONE unsharded run)."""
    if n_studies not in _REF:
        _REF[n_studies] = serve_scenario(None, n_studies=n_studies)
    return _REF[n_studies]


@pytest.mark.parametrize("n_dev", [1, 4])
def test_mesh_serve_64_study_scenario_bitwise(cpu_mesh, n_dev):
    """THE acceptance pin: the mesh-sharded engine is bitwise the
    single-device engine through the full 64-slot scenario (60
    tenants + churn + the quarantined NaN tenant) -- churn, dirty-slot
    re-materialization, backlog drains, quarantine -- on a 1-device
    mesh AND a 4-virtual-device mesh."""
    ref_streams, ref_counters, ref_q = _reference()
    streams, counters, quarantined = serve_scenario(cpu_mesh(n_dev))

    assert quarantined and ref_q, "NaN tenant must be evicted"
    assert counters["evictions"] == ref_counters["evictions"] == 1
    for name, stream in ref_streams.items():
        assert streams[name] == stream, (
            f"study {name} diverged on the {n_dev}-device mesh"
        )
    assert counters["mesh_shards"] == n_dev
    # same number of ROUND dispatches; the mesh run may pay extra
    # masked-delta drains where the unsharded engine's full remat
    # absorbed a sibling shard's staged backlog as a side effect
    assert (
        counters["dispatch_count"] - counters["delta_drain_dispatches"]
        == ref_counters["dispatch_count"]
        - ref_counters["delta_drain_dispatches"]
    )
    if n_dev > 1:
        # shard-locality really engaged: the out-of-order dirty slot,
        # the quarantine re-materializations, and the churn joins all
        # re-upload single shards instead of the whole stacked state
        assert counters["shard_restacks"] > 0
        assert counters["upload_bytes"] < ref_counters["upload_bytes"]


def test_mesh_serve_uneven_churn_non_pow2_shards(cpu_mesh):
    """REGRESSION (the slot-capacity satellite): on a 3-shard mesh the
    pow2 capacity schedule alone would leave the slot axis indivisible
    -- capacities must round up to a multiple of the study-axis size,
    and the padded dead slots must stay invisible through uneven churn
    (close-before-first-dispatch leaves survivors on high slots)."""
    mesh = cpu_mesh(3)
    svc = SuggestService(
        SPACE, max_batch=16, background=False,
        n_startup_jobs=N_STARTUP, mesh=mesh, **ALGO_KW,
    )
    handles = [svc.create_study(f"u{i}", seed=50 + i) for i in range(5)]
    handles[0].close()  # frees a slot BEFORE the first dispatch
    survivors = handles[1:]
    streams = {}
    drive(svc, survivors, streams, 3)
    assert svc.scheduler._slot_cap % 3 == 0
    state = svc.scheduler._state
    assert state.values.shape[0] == svc.scheduler._slot_cap
    counters = dict(svc.counters)
    svc.shutdown()

    ref = SuggestService(
        SPACE, max_batch=16, background=False,
        n_startup_jobs=N_STARTUP, **ALGO_KW,
    )
    rhandles = [ref.create_study(f"u{i}", seed=50 + i) for i in range(5)]
    rhandles[0].close()
    rstreams = {}
    drive(ref, rhandles[1:], rstreams, 3)
    ref.shutdown()
    assert streams == rstreams, "uneven churn diverged on 3 shards"
    assert counters["mesh_shards"] == 3


def test_slot_capacity_rounds_to_shard_multiple():
    # the historical pow2 schedule is the shards=1 degenerate case
    assert slot_capacity(1, 64) == 4
    assert slot_capacity(5, 64) == 8
    assert slot_capacity(100, 64) == 64
    # shard rounding: up to a multiple of the study-axis size
    assert slot_capacity(1, 64, shards=4) == 4
    assert slot_capacity(5, 64, shards=4) == 8
    assert slot_capacity(5, 64, shards=3) == 9
    assert slot_capacity(1, 64, shards=3) == 6
    assert slot_capacity(33, 64, shards=3) == 66  # pads past max_batch
    assert slot_capacity(3, 2, shards=4) == 4
    for n in (1, 3, 5, 17):
        for m in (1, 2, 3, 4, 5, 8):
            cap = slot_capacity(n, 64, shards=m)
            assert cap % m == 0 and cap >= min(n, 4)


def test_mesh_slot_placement_stripes_across_shards(cpu_mesh):
    """Shard-aware placement: new studies spread over the mesh instead
    of piling onto shard 0, so every shard's re-materializations stay
    small."""
    svc = SuggestService(
        SPACE, max_batch=16, background=False,
        n_startup_jobs=N_STARTUP, mesh=cpu_mesh(4), **ALGO_KW,
    )
    for i in range(4):
        svc.create_study(f"p{i}", seed=i)
    sched = svc.scheduler
    cap = max(
        sched._slot_cap,
        slot_capacity(4, 16, shards=4),
    )
    blk = cap // 4
    shards = sorted(s // blk for s in sched._slots)
    assert shards == [0, 1, 2, 3], (
        f"expected one study per shard, got slot->shard {shards}"
    )
    svc.shutdown()


def test_subprocess_harness_forces_device_count():
    """The subprocess half of the multi-device harness: a child pinned
    to exactly 4 virtual CPU devices runs a mesh parity check the
    parent's device count cannot influence."""
    from hyperopt_tpu.parallel.mesh import subprocess_env_with_devices

    code = """
import jax
assert jax.device_count() == 4, jax.device_count()
import numpy as np
from hyperopt_tpu import hp
from hyperopt_tpu.parallel.mesh import study_mesh
from hyperopt_tpu.serve import SuggestService

space = {"x": hp.uniform("x", -2.0, 2.0)}

def run(mesh):
    svc = SuggestService(space, max_batch=4, background=False,
                         n_startup_jobs=1, n_cand=4, mesh=mesh)
    hs = [svc.create_study(f"s{i}", seed=i) for i in range(4)]
    streams = []
    for _ in range(2):
        futs = [h.ask_async() for h in hs]
        svc.pump()
        for h, f in zip(hs, futs):
            tid, vals = f.result(timeout=60)
            streams.append(vals)
            h.tell(tid, vals["x"] ** 2)
    svc.shutdown()
    return streams

assert run(study_mesh(4)) == run(None)
print("MESH_SUBPROCESS_OK")
"""
    out = subprocess.run(
        [sys.executable, "-c", code],
        env=subprocess_env_with_devices(4),
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "MESH_SUBPROCESS_OK" in out.stdout


# ---------------------------------------------------------------------------
# sharded PBT / device-ASHA parity
# ---------------------------------------------------------------------------


def _pbt_train_fn():
    import jax
    import jax.numpy as jnp

    def train_fn(state, hypers, key):
        # shared (member-position-independent) noise from the step key
        # + per-member elementwise math: the vmapped-contract norm
        noise = jax.random.normal(key, (), dtype=jnp.float32) * 0.01
        theta = state["theta"] - hypers["lr"] * 2.0 * (
            state["theta"] - 0.7
        ) + noise
        return {"theta": theta}, (theta - 0.7) ** 2

    return train_fn


@pytest.mark.parametrize("n_dev", [1, 2, 4])
def test_pbt_shard_map_exploit_boundary_parity(cpu_mesh, n_dev):
    """Sharded-PBT parity at equal population: the shard_map schedule
    (per-shard member blocks, all-gathers only at exploit boundaries)
    is bitwise the unsharded schedule -- loss history, final hypers,
    final member state, and the resumed segment."""
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.pbt import compile_pbt

    train_fn = _pbt_train_fn()
    init = {"theta": jnp.linspace(0.0, 5.0, 16, dtype=jnp.float32)}
    kw = dict(
        hyper_bounds={"lr": (1e-3, 1.0)}, pop_size=16,
        exploit_every=3, n_rounds=4,
    )
    plain = compile_pbt(train_fn, init, **kw)
    ref = plain(seed=7)
    sharded = compile_pbt(
        train_fn, init, mesh=cpu_mesh(n_dev, axis="trial"),
        trial_axis="trial", shard_mode="shard_map", **kw,
    )
    out = sharded(seed=7)
    np.testing.assert_array_equal(
        np.asarray(out["loss_history"]), np.asarray(ref["loss_history"])
    )
    for n in ref["hypers"]:
        np.testing.assert_array_equal(out["hypers"][n], ref["hypers"][n])
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(out["state"]["theta"])),
        np.asarray(jax.device_get(ref["state"]["theta"])),
    )
    assert out["best_loss"] == ref["best_loss"]
    assert out["best_index"] == ref["best_index"]

    # resume parity: a second segment from the sharded result matches
    # the unsharded second segment bitwise
    out2 = sharded(seed=7, init=out)
    ref2 = plain(seed=7, init=ref)
    np.testing.assert_array_equal(
        np.asarray(out2["loss_history"]),
        np.asarray(ref2["loss_history"]),
    )


def test_pbt_shard_map_validation():
    import jax.numpy as jnp

    from hyperopt_tpu.parallel.mesh import mesh_from_spec
    from hyperopt_tpu.pbt import compile_pbt

    train_fn = _pbt_train_fn()
    init = {"theta": jnp.zeros((6,), jnp.float32)}
    with pytest.raises(ValueError, match="requires mesh"):
        compile_pbt(
            train_fn, init, {"lr": (1e-3, 1.0)}, pop_size=6,
            shard_mode="shard_map",
        )
    mesh = mesh_from_spec((4,), ("trial",))
    with pytest.raises(ValueError, match="divide"):
        compile_pbt(
            train_fn, init, {"lr": (1e-3, 1.0)}, pop_size=6,
            mesh=mesh, trial_axis="trial", shard_mode="shard_map",
        )
    with pytest.raises(ValueError, match="shard_mode"):
        compile_pbt(
            train_fn, init, {"lr": (1e-3, 1.0)}, pop_size=8,
            mesh=mesh, trial_axis="trial", shard_mode="nonsense",
        )


@pytest.mark.parametrize("n_dev", [1, 4])
def test_sha_shard_map_rung_parity(cpu_mesh, n_dev):
    """Sharded device-ASHA: every rung's population shards over a
    per-rung sub-mesh (gcd keeps late tiny rungs divisible) and the
    ladder -- per-rung bests, winner, hypers -- is bitwise the
    unsharded one."""
    import jax.numpy as jnp

    from hyperopt_tpu.hyperband import compile_sha

    train_fn = _pbt_train_fn()
    init = {"theta": jnp.linspace(0.5, 5.0, 8, dtype=jnp.float32)}
    kw = dict(
        hyper_bounds={"lr": (1e-3, 1.0)}, n_configs=8, eta=2,
        steps_per_rung=2,
    )
    ref = compile_sha(train_fn, init, **kw)(seed=9)
    out = compile_sha(
        train_fn, init, mesh=cpu_mesh(n_dev, axis="trial"),
        trial_axis="trial", shard_mode="shard_map", **kw,
    )(seed=9)
    assert out["best_loss"] == ref["best_loss"]
    assert out["best_hypers"] == ref["best_hypers"]
    assert out["best_index"] == ref["best_index"]
    assert [r["best_loss"] for r in out["rungs"]] == [
        r["best_loss"] for r in ref["rungs"]
    ]
    # the per-rung sub-meshes really shrink with the rung population
    runner = compile_sha(
        train_fn, init, mesh=cpu_mesh(n_dev, axis="trial"),
        trial_axis="trial", shard_mode="shard_map", **kw,
    )
    sizes = [
        int(np.prod(list(s.mesh.shape.values())))
        for s in runner._rung_shardings
    ]
    assert sizes == [math.gcd(8 // 2**r, n_dev) for r in range(4)]


def test_mesh_programs_registered_in_ir_manifest():
    """The tooling satellite: the graftmesh program families are
    registered and their contracts -- including the donation verified
    under shard_map (GL403 reads the multi-device buffer-donor
    attributes) -- are pinned in the committed manifest."""
    import os

    from hyperopt_tpu.analysis.ir import load_contracts
    from hyperopt_tpu.ops.compile import registered_programs

    specs = registered_programs()
    for name in ("serve.batched_step_mesh", "serve.batched_delta_mesh",
                 "pbt.sharded_schedule", "hyperband.sha_rung_mesh"):
        assert name in specs, name
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    manifest = load_contracts(
        os.path.join(repo, "program_contracts.json")
    )["programs"]
    assert manifest["serve.batched_step_mesh"]["donation"] == [1, 2, 3, 4]
    assert manifest["serve.batched_delta_mesh"]["donation"] == [0, 1, 2, 3]
    assert manifest["pbt.sharded_schedule"]["donation"] == []
    assert manifest["hyperband.sha_rung_mesh"]["donation"] == []
