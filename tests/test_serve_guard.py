"""graftguard: overload protection, poisoned-tenant isolation, and the
dispatch watchdog (ISSUE 9).

The acceptance contract, pinned deterministically:

* OVERLOAD: submits past the bounded queue (or the per-study fairness
  cap) are refused PROMPTLY with a typed ``Overloaded`` carrying a
  retry-after hint, admission happens before the seed draw (shedding
  never perturbs an admitted stream), and every admitted ask still
  resolves with bounded latency;
* POISON: a tenant telling NaN (or a device fault scribbling NaN into
  its batched output) trips the fused finite-check, fails ONLY its own
  client with a typed error, re-materializes from host truth, and is
  evicted after K consecutive trips -- sibling streams stay bitwise
  equal to the same-seed no-fault run;
* WATCHDOG: a hung dispatch times out against the deadline and a
  transiently raising dispatch retries once against a re-materialized
  stacked state -- bitwise invisibly; deterministic program bugs skip
  the retry and circuit-break the batcher into reject-with-Overloaded;
* ZERO LOSS: across the full chaos scenario every submitted ask
  resolves with a suggestion or a typed error -- nothing is silently
  dropped -- and the whole scenario replays bitwise under the same
  seeds.
"""

import time

import numpy as np
import pytest

from hyperopt_tpu.distributed.faults import DeviceFaultPlan, FaultPlan
from hyperopt_tpu.exceptions import (
    DeadlineExpired,
    Overloaded,
    ServeError,
    StudyPoisoned,
    StudyQuarantined,
)
from hyperopt_tpu.serve import SuggestService
from test_serve import ALGO_KW, N_STARTUP, SPACE, loss_fn, solo_stream

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _lockdep_armed(monkeypatch):
    # the guard scenarios exercise the watchdog/circuit paths where a
    # second lock would be easiest to smuggle in -- lockdep watches
    # every acquisition the whole suite long
    from hyperopt_tpu.analysis import lockdep

    dep = lockdep.arm_scheduler_class(monkeypatch)
    yield dep
    assert dep.inversions == 0, dep.errors


def _svc(**kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("background", False)
    kw.setdefault("n_startup_jobs", N_STARTUP)
    for k, v in ALGO_KW.items():
        kw.setdefault(k, v)
    return SuggestService(SPACE, **kw)


# ---------------------------------------------------------------------------
# admission control & load shedding
# ---------------------------------------------------------------------------


def test_overload_storm_sheds_typed_and_keeps_admitted_streams_pure():
    """A submit storm past the high-water mark: typed ``Overloaded``
    with a positive retry-after, every ADMITTED ask served with
    bounded latency, and -- because admission precedes the seed draw --
    the admitted suggestion stream is exactly the solo prefix."""
    svc = _svc(max_queue=6, study_queue_cap=2)
    sched = svc.scheduler
    handles = [svc.create_study(f"ov{i}", seed=300 + i) for i in range(4)]
    futs, n_shed = [], 0
    for _ in range(5):  # 20 submits against queue 6 / per-study cap 2
        for h in handles:
            try:
                futs.append((h, h.ask_async()))
            except Overloaded as e:
                n_shed += 1
                assert e.retry_after is not None and e.retry_after > 0
                assert e.reason in ("queue_full", "study_queue_cap")
    assert n_shed > 0
    assert sched.shed_count == n_shed
    assert sched.admitted_count == len(futs)
    streams = {}
    while any(not f.done() for _, f in futs):
        svc.pump()
    for h, f in futs:
        tid, vals = f.result(timeout=0)
        streams.setdefault(h.name, []).append(vals)
    # bounded latency for admitted requests (loose wall-clock pin: the
    # claim is 'bounded', not a perf number)
    lats = sorted(sched.ask_latencies)
    assert lats[int(0.99 * (len(lats) - 1))] < 30.0
    # seed-stream purity: sheds consumed nothing, so each study's
    # admitted stream is its solo stream's prefix (no tells here, and
    # asks between tells re-draw from the same posterior, so the solo
    # reference must replay the same no-tell cadence)
    for i, h in enumerate(handles):
        n = len(streams[h.name])
        ref = np.random.default_rng(300 + i)
        admitted_seeds = [int(ref.integers(2**31 - 1)) for _ in range(n)]
        st = svc.scheduler.study(h.name)
        assert st.n_asks == n
        # the NEXT draw continues the unperturbed stream
        nxt = svc.scheduler.submit_ask(st)
        assert nxt.seed == int(ref.integers(2**31 - 1))
        assert admitted_seeds  # the storm admitted something per study
    svc.shutdown()


def test_submit_with_expired_deadline_is_shed_before_the_seed_draw():
    svc = _svc()
    h = svc.create_study("dead", seed=7)
    st = svc.scheduler.study("dead")
    with pytest.raises(DeadlineExpired):
        svc.scheduler.submit_ask(st, deadline=time.perf_counter() - 1.0)
    assert st.n_asks == 0 and st.next_tid == 0
    assert svc.scheduler.shed_count == 1
    # the stream was not perturbed: the next admitted seed is draw #0
    req = svc.scheduler.submit_ask(st)
    assert req.seed == int(np.random.default_rng(7).integers(2**31 - 1))
    svc.shutdown()


def test_queued_ask_expiring_is_dropped_not_dispatched():
    """The slow-client path: an ask whose deadline passes while queued
    is shed at pick time and never consumes a dispatch slot."""
    svc = _svc()
    h = svc.create_study("slow", seed=9)
    st = svc.scheduler.study("slow")
    expired = svc.scheduler.submit_ask(
        st, deadline=time.perf_counter() + 0.005
    )
    time.sleep(0.02)
    fresh = svc.scheduler.submit_ask(st)
    served = svc.pump()
    assert served == 1  # only the fresh ask reached the device
    with pytest.raises(DeadlineExpired):
        expired.future.result(timeout=0)
    assert fresh.future.result(timeout=0)[0] == fresh.tid
    assert not svc.scheduler._asks  # nothing stranded in the queue
    assert svc.pump() == 0  # and no zombie slot consumed later
    svc.shutdown()


def test_ask_timeout_drops_the_queued_request():
    """``ask(timeout=...)`` on the background service: expiry while
    queued drops the request (typed DeadlineExpired), leaving no
    stranded future to consume a later dispatch slot."""
    svc = _svc(background=True, max_wait_ms=2000.0)
    svc.create_study("t0", seed=1)  # a second tenant keeps _ready false
    h = svc.create_study("t1", seed=2)
    with pytest.raises(DeadlineExpired):
        h.ask(timeout=0.05)
    assert not svc.scheduler._asks
    assert svc.scheduler.shed_count == 1
    svc.shutdown()


def test_scheduler_queue_is_bounded():
    """REGRESSION (the PR-8 leak class): the ask queue itself is capped
    -- ``max_queue`` defaults to ``4 * max_batch`` and the 4 *
    max_batch + 1st un-served submit is refused, not queued."""
    svc = _svc(max_batch=4, study_queue_cap=10**9)
    sched = svc.scheduler
    assert sched.max_queue == 16
    h = svc.create_study("q", seed=1)
    st = sched.study("q")
    for _ in range(16):
        sched.submit_ask(st)
    assert len(sched._asks) == 16
    with pytest.raises(Overloaded) as ei:
        sched.submit_ask(st)
    assert ei.value.reason == "queue_full"
    assert len(sched._asks) == 16  # refused, not enqueued
    svc.shutdown()


# ---------------------------------------------------------------------------
# poisoned-tenant isolation
# ---------------------------------------------------------------------------


def test_nan_tell_quarantines_evicts_and_pins_siblings_bitwise():
    """One tenant tells NaN: its own asks fail typed
    (StudyPoisoned -> StudyQuarantined at K trips), it is evicted, and
    the sibling's stream stays bitwise equal to its solo run."""
    svc = _svc(max_batch=4)
    ps = svc.ps
    good = svc.create_study("good", seed=21)
    bad = svc.create_study("bad", seed=22)
    bad.tell(0, float("nan"), vals={"x": 0.5, "lr": 0.1, "q": 2.0, "c": 0})
    sched = svc.scheduler
    streams, bad_errors = {"good": []}, []
    for _ in range(5):
        fg = good.ask_async()
        fb = None
        if not sched.study("bad").quarantined:
            fb = bad.ask_async()
        svc.pump()
        tid, vals = fg.result(timeout=10)
        streams["good"].append(vals)
        good.tell(tid, loss_fn(vals))
        if fb is not None:
            bad_errors.append(fb.exception(timeout=10))
    assert streams["good"] == solo_stream(ps, 21, 5), (
        "sibling stream disturbed by a poisoned tenant"
    )
    assert [type(e).__name__ for e in bad_errors] == [
        "StudyPoisoned", "StudyPoisoned", "StudyQuarantined",
    ]
    assert sched.quarantine_count == 3 and sched.evictions == 1
    with pytest.raises(StudyQuarantined):
        bad.ask_async()
    with pytest.raises(StudyQuarantined):
        bad.tell(99, 1.0, vals={"x": 0.0, "lr": 0.1, "q": 1.0, "c": 0})
    svc.shutdown()


def test_transient_device_nan_heals_via_rematerialization():
    """A ONE-SHOT device NaN (host truth clean): the victim's tripped
    ask fails typed, the slot re-materializes from host truth, and the
    very next ask serves -- no eviction, trips reset."""
    dev = DeviceFaultPlan(nan_study="v", nan_at=2, nan_count=1)
    plan = FaultPlan(seed=0, device=dev)
    svc = _svc(max_batch=4, fs=plan.fs())
    v = svc.create_study("v", seed=31)
    outcomes = []
    for _ in range(4):
        f = v.ask_async()
        svc.pump()
        if f.exception(timeout=10) is not None:
            outcomes.append(type(f.exception()).__name__)
        else:
            tid, vals = f.result()
            outcomes.append("served")
            v.tell(tid, loss_fn(vals))
    assert outcomes == ["served", "StudyPoisoned", "served", "served"]
    sched = svc.scheduler
    assert sched.quarantine_count == 1 and sched.evictions == 0
    assert sched.study("v").poison_trips == 0  # reset by the clean round
    svc.shutdown()


# ---------------------------------------------------------------------------
# dispatch watchdog
# ---------------------------------------------------------------------------


def test_watchdog_times_out_hung_dispatch_and_recovers_bitwise():
    """An injected dispatch hang past the deadline: DispatchTimeout,
    one retry against a re-materialized state, and the client stream
    is bitwise what the no-fault run serves (the retry reuses the
    already-drawn per-ask seeds)."""
    dev = DeviceFaultPlan(hang_at=3, hang_s=0.5)
    plan = FaultPlan(seed=0, device=dev)
    svc = _svc(max_batch=4, fs=plan.fs())
    ps = svc.ps
    h = svc.create_study("w", seed=41)
    stream = []
    for rnd in range(4):
        f = h.ask_async()
        svc.pump()
        tid, vals = f.result(timeout=10)
        stream.append(vals)
        h.tell(tid, loss_fn(vals))
        if rnd == 0:
            # arm the watchdog AFTER the compile round: the deadline
            # bounds dispatch execution, not first-trace compilation
            svc.scheduler.dispatch_timeout = 0.2
    assert stream == solo_stream(ps, 41, 4), (
        "watchdog recovery perturbed the suggestion stream"
    )
    sched = svc.scheduler
    assert sched.watchdog_timeouts == 1
    assert sched.watchdog_retries == 1
    assert sched.watchdog_recoveries == 1
    assert len(sched.watchdog_recovery_ms) == 1
    svc.shutdown()


def test_deterministic_program_bug_skips_retry_and_opens_circuit():
    """A dispatch raising a NON-transient error: no pointless retry,
    the picked asks fail typed, the circuit breaker opens into
    reject-with-Overloaded, and reset_circuit() restores service."""
    dev = DeviceFaultPlan(fatal_at=1)
    plan = FaultPlan(seed=0, device=dev)
    svc = _svc(max_batch=4, fs=plan.fs())
    svc.scheduler.circuit_threshold = 1
    h = svc.create_study("c", seed=51)
    f = h.ask_async()
    assert svc.pump() == 0
    with pytest.raises(RuntimeError, match="injected deterministic"):
        f.result(timeout=0)
    sched = svc.scheduler
    assert sched.watchdog_retries == 0  # deterministic bug: no retry
    assert sched.circuit_open
    with pytest.raises(Overloaded) as ei:
        h.ask_async()
    assert ei.value.reason == "circuit_open"
    sched.reset_circuit()
    f2 = h.ask_async()
    svc.pump()
    assert f2.result(timeout=10)[0] == f2.tid if hasattr(f2, "tid") else True
    svc.shutdown()


def test_transient_raise_storm_is_bitwise_invisible():
    """10% transient dispatch raises (burst 1): every raise recovers
    through the retry, and every study's stream is bitwise the
    no-fault run's."""
    streams_by_run = []
    for dev in (None, DeviceFaultPlan(seed=2, raise_rate=0.4, burst=1)):
        plan = FaultPlan(seed=0, device=dev)
        svc = _svc(max_batch=4, fs=plan.fs())
        handles = [svc.create_study(f"r{i}", seed=60 + i) for i in range(3)]
        streams = {}
        for _ in range(6):
            futs = [(h, h.ask_async()) for h in handles]
            svc.pump()
            for h, f in futs:
                tid, vals = f.result(timeout=10)
                streams.setdefault(h.name, []).append(vals)
                h.tell(tid, loss_fn(vals))
        if dev is not None:
            assert dev.stats["device:raise"] > 0, "storm never fired"
            assert svc.scheduler.watchdog_recoveries == \
                dev.stats["device:raise"]
        streams_by_run.append(streams)
        svc.shutdown()
    assert streams_by_run[0] == streams_by_run[1], (
        "transient dispatch raises perturbed a suggestion stream"
    )


# ---------------------------------------------------------------------------
# health / ready / draining
# ---------------------------------------------------------------------------


def test_health_ready_and_draining_shutdown():
    svc = _svc(max_batch=4)
    h = svc.create_study("hl", seed=71)
    assert svc.ready()
    snap = svc.health()
    assert snap["status"] == "ok" and snap["ready"]
    assert snap["studies"] == 1 and snap["queue_depth"] == 0
    assert snap["counters"]["shed_count"] == 0
    # draining: queued work still served, new submits refused typed
    f = h.ask_async()
    svc.scheduler.drain()
    assert not svc.ready()
    assert svc.health()["status"] == "draining"
    with pytest.raises(Overloaded) as ei:
        h.ask_async()
    assert ei.value.reason == "draining"
    svc.pump()
    assert f.result(timeout=10)  # the queued ask was not abandoned
    svc.drain(timeout=5.0)
    assert svc.health()["status"] == "stopped"


def test_socket_transport_maps_guard_errors_and_health():
    import json
    import socket
    import threading

    from hyperopt_tpu.serve.service import serve_forever

    svc = SuggestService(
        SPACE, background=True, max_wait_ms=1.0, n_startup_jobs=2,
        max_queue=0, **ALGO_KW,
    )
    server = serve_forever(svc, port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        host, port = server.server_address[:2]
        with socket.create_connection((host, port), timeout=10) as sock:
            f = sock.makefile("rw")

            def rpc(**req):
                f.write(json.dumps(req) + "\n")
                f.flush()
                return json.loads(f.readline())

            r = rpc(op="health")
            assert r["ok"] and r["status"] == "ok" and r["ready"]
            assert rpc(op="ready")["ready"]
            assert rpc(op="create_study", name="g", seed=1)["ok"]
            # max_queue=0: every ask is shed -> the structured refusal
            r = rpc(op="ask", study="g", timeout=5)
            assert not r["ok"]
            assert r["error_type"] == "Overloaded"
            assert r["reason"] == "queue_full"
            assert r["retry_after"] > 0
    finally:
        server.shutdown()
        server.server_close()
        svc.shutdown()


# ---------------------------------------------------------------------------
# THE acceptance scenario: 64-study churn under the full fault plan
# ---------------------------------------------------------------------------

VICTIM = "s07"


def _run_churn(faulted, n_rounds=6):
    """The 64-study churn workload: two closes waves, one join wave,
    every open study asking+telling every round.  Returns per-study
    outcome streams (vals dicts for served asks, typed error names for
    refused/failed ones) plus the scheduler counters."""
    dev = DeviceFaultPlan(
        seed=13, nan_study=VICTIM, nan_at=3,  # persistent: drives eviction
        hang_at=4, hang_s=0.5, raise_rate=0.10, burst=1,
    ) if faulted else None
    plan = FaultPlan(seed=13, device=dev)
    svc = SuggestService(
        SPACE, max_batch=64, background=False, n_startup_jobs=N_STARTUP,
        fs=plan.fs(), dispatch_timeout=None if dev is None else 0.25,
        **ALGO_KW,
    )
    handles = {}
    for i in range(64):
        name = f"s{i:02d}"
        handles[name] = svc.create_study(name, seed=100 + i)
    outcomes = {name: [] for name in handles}
    submitted = resolved = 0
    for rnd in range(n_rounds):
        if rnd == 2:  # churn: a leave wave frees low slots
            for name in ("s20", "s21", "s22", "s23"):
                handles.pop(name).close()
        if rnd == 4:  # churn: a join wave reuses them
            for j in range(4):
                name = f"j{j}"
                handles[name] = svc.create_study(name, seed=900 + j)
                outcomes[name] = []
        futs = []
        for name, h in handles.items():
            try:
                futs.append((name, h, h.ask_async()))
                submitted += 1
            except ServeError as e:  # refusal IS a typed resolution
                outcomes[name].append(type(e).__name__)
        svc.pump()
        for name, h, f in futs:
            exc = f.exception(timeout=30)
            resolved += 1
            if exc is not None:
                assert isinstance(exc, (StudyPoisoned, StudyQuarantined)), (
                    f"untyped failure for {name}: {exc!r}"
                )
                outcomes[name].append(type(exc).__name__)
            else:
                tid, vals = f.result()
                outcomes[name].append(vals)
                h.tell(tid, loss_fn(vals))
    counters = dict(svc.counters)
    svc.shutdown()
    assert resolved == submitted  # zero asks silently lost
    return outcomes, counters


def test_chaos_64_study_churn_siblings_bitwise_and_victim_quarantined():
    """The ISSUE-9 acceptance run: NaN injection on one tenant + one
    dispatch hang + 10% transient dispatch raises over a 64-study
    churn workload.  The victim is quarantined with typed errors and
    evicted; EVERY other study's stream is bitwise the same-seed
    no-fault run's; zero asks are lost; and the whole faulted scenario
    replays bitwise under the same seeds."""
    clean, _ = _run_churn(faulted=False)
    faulted, counters = _run_churn(faulted=True)
    replay, replay_counters = _run_churn(faulted=True)

    # deterministic chaos: the faulted scenario replays bitwise
    assert faulted == replay
    for k in ("dispatch_count", "quarantine_count", "evictions",
              "watchdog_timeouts", "watchdog_retries", "shed_count",
              "admitted_count"):
        assert counters[k] == replay_counters[k], k

    # the victim was quarantined: typed errors only, then eviction
    bad = [o for o in faulted[VICTIM] if isinstance(o, str)]
    assert bad, "the NaN injection never tripped the finite-check"
    assert set(bad) <= {"StudyPoisoned", "StudyQuarantined"}
    assert counters["evictions"] == 1
    assert counters["quarantine_count"] >= 3
    served_prefix = [o for o in faulted[VICTIM] if not isinstance(o, str)]
    assert served_prefix == clean[VICTIM][: len(served_prefix)]

    # every sibling stream is bitwise the no-fault run's
    for name, stream in faulted.items():
        if name == VICTIM:
            continue
        assert stream == clean[name], (
            f"study {name} diverged under the fault plan"
        )

    # the armed faults really fired and really recovered
    assert counters["watchdog_timeouts"] == 1  # the hang
    assert counters["watchdog_recoveries"] == counters["watchdog_retries"]
    assert counters["watchdog_retries"] >= 1
