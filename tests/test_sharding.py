"""Sharded-suggest tests on the virtual 8-device CPU mesh (SURVEY.md SS4:
run the real thing small -- xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.parallel import (
    default_mesh,
    device_count,
    mesh_from_spec,
    multihost,
    sharded_suggest,
)


def test_virtual_mesh_has_8_devices():
    assert device_count() == 8


def test_default_mesh_shape():
    mesh = default_mesh()
    assert mesh.shape == {"cand": 8}


def test_mesh_from_spec_2d():
    mesh = mesh_from_spec((2, 4), ("trial", "cand"))
    assert mesh.shape == {"trial": 2, "cand": 4}
    with pytest.raises(ValueError):
        mesh_from_spec((4, 4), ("trial", "cand"))


def test_sharded_suggest_end_to_end():
    trials = Trials()
    best = fmin(
        lambda x: (x - 3.0) ** 2,
        hp.uniform("x", -10, 10),
        algo=sharded_suggest,
        max_evals=45,
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    assert len(trials) == 45
    assert trials.best_trial["result"]["loss"] < 2.5


def test_sharded_suggest_mixed_conditional_space():
    space = hp.choice(
        "c",
        [
            {"kind": "a", "lr": hp.loguniform("lr_a", -5, 0)},
            {"kind": "b", "x": hp.uniform("x_b", 0, 1), "n": hp.randint("n_b", 5)},
        ],
    )

    def obj(cfg):
        return cfg["lr"] if cfg["kind"] == "a" else cfg["x"]

    trials = Trials()
    fmin(
        obj, space, algo=sharded_suggest, max_evals=40, trials=trials,
        rstate=np.random.default_rng(1), show_progressbar=False,
    )
    for t in trials.trials:
        vals = t["misc"]["vals"]
        if vals["c"][0] == 0:
            assert vals["lr_a"] and not vals["x_b"]
        else:
            assert vals["x_b"] and vals["n_b"]
    assert np.isfinite(trials.best_trial["result"]["loss"])


def test_sharded_matches_unsharded_quality():
    """Sharded and unsharded TPE should reach comparable losses (same
    algorithm, more candidates)."""
    from hyperopt_tpu import tpe_jax

    def run(algo):
        trials = Trials()
        fmin(
            lambda x: (x - 3.0) ** 2, hp.uniform("x", -10, 10), algo=algo,
            max_evals=60, trials=trials, rstate=np.random.default_rng(2),
            show_progressbar=False,
        )
        return trials.best_trial["result"]["loss"]

    sharded_loss = run(sharded_suggest)
    unsharded_loss = run(tpe_jax.suggest)
    assert sharded_loss < 1.0
    assert unsharded_loss < 1.0


def test_multihost_single_process_degenerates():
    assert not multihost.is_multihost()
    assert multihost.process_index() == 0
    assert multihost.process_count() == 1
    v = np.ones((2, 3))
    a = np.ones((2, 3), bool)
    v2, a2 = multihost.broadcast_configs(v, a)
    np.testing.assert_array_equal(np.asarray(v2), v)
    assert multihost.shard_ids_for_host([1, 2, 3, 4], 0, 2) == [1, 3]
    assert multihost.shard_ids_for_host([1, 2, 3, 4], 1, 2) == [2, 4]
    assert multihost.initialize() is False
