"""Sharded-suggest tests on the virtual 8-device CPU mesh (SURVEY.md SS4:
run the real thing small -- xla_force_host_platform_device_count=8)."""

import os

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.parallel import (
    default_mesh,
    device_count,
    mesh_from_spec,
    multihost,
    sharded_suggest,
)


def test_virtual_mesh_has_8_devices():
    assert device_count() == 8


def test_default_mesh_shape():
    mesh = default_mesh()
    assert mesh.shape == {"cand": 8}


def test_mesh_from_spec_2d():
    mesh = mesh_from_spec((2, 4), ("trial", "cand"))
    assert mesh.shape == {"trial": 2, "cand": 4}
    with pytest.raises(ValueError):
        mesh_from_spec((4, 4), ("trial", "cand"))


def test_sharded_suggest_end_to_end():
    trials = Trials()
    best = fmin(
        lambda x: (x - 3.0) ** 2,
        hp.uniform("x", -10, 10),
        algo=sharded_suggest,
        max_evals=45,
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    assert len(trials) == 45
    assert trials.best_trial["result"]["loss"] < 2.5


def test_sharded_suggest_mixed_conditional_space():
    space = hp.choice(
        "c",
        [
            {"kind": "a", "lr": hp.loguniform("lr_a", -5, 0)},
            {"kind": "b", "x": hp.uniform("x_b", 0, 1), "n": hp.randint("n_b", 5)},
        ],
    )

    def obj(cfg):
        return cfg["lr"] if cfg["kind"] == "a" else cfg["x"]

    trials = Trials()
    fmin(
        obj, space, algo=sharded_suggest, max_evals=40, trials=trials,
        rstate=np.random.default_rng(1), show_progressbar=False,
    )
    for t in trials.trials:
        vals = t["misc"]["vals"]
        if vals["c"][0] == 0:
            assert vals["lr_a"] and not vals["x_b"]
        else:
            assert vals["x_b"] and vals["n_b"]
    assert np.isfinite(trials.best_trial["result"]["loss"])


@pytest.mark.slow
def test_sharded_matches_unsharded_quality():
    """Sharded and unsharded TPE should reach comparable losses (same
    algorithm, more candidates)."""
    from hyperopt_tpu import tpe_jax

    def run(algo):
        trials = Trials()
        fmin(
            lambda x: (x - 3.0) ** 2, hp.uniform("x", -10, 10), algo=algo,
            max_evals=60, trials=trials, rstate=np.random.default_rng(2),
            show_progressbar=False,
        )
        return trials.best_trial["result"]["loss"]

    sharded_loss = run(sharded_suggest)
    unsharded_loss = run(tpe_jax.suggest)
    assert sharded_loss < 1.0
    assert unsharded_loss < 1.0


@pytest.mark.slow
def test_sharded_atpe_end_to_end():
    """Adaptive TPE with the warm-path candidate sweep sharded over the
    8-device mesh (``atpe_jax.suggest(mesh=)``): converges, and the
    speculative cache composes (mesh identity in the cache key)."""
    from functools import partial

    import numpy as np

    from hyperopt_tpu import atpe_jax
    from hyperopt_tpu.parallel import mesh_from_spec

    mesh = mesh_from_spec((8,), ("cand",))

    def run(**kw):
        trials = Trials()
        fmin(
            lambda x: (x - 3.0) ** 2, hp.uniform("x", -10, 10),
            algo=partial(atpe_jax.suggest, mesh=mesh, **kw),
            max_evals=60, trials=trials, rstate=np.random.default_rng(2),
            show_progressbar=False,
        )
        return min(trials.losses())

    assert run() < 1.0
    assert run(speculative=4) < 2.0


def test_multihost_single_process_degenerates():
    assert not multihost.is_multihost()
    assert multihost.process_index() == 0
    assert multihost.process_count() == 1
    v = np.ones((2, 3))
    a = np.ones((2, 3), bool)
    v2, a2 = multihost.broadcast_configs(v, a)
    np.testing.assert_array_equal(np.asarray(v2), v)
    assert multihost.shard_ids_for_host([1, 2, 3, 4], 0, 2) == [1, 3]
    assert multihost.shard_ids_for_host([1, 2, 3, 4], 1, 2) == [2, 4]
    assert multihost.initialize() is False


@pytest.mark.slow
@pytest.mark.xfail(
    strict=True,
    reason="jaxlib 0.4.36's CPU backend removed multiprocess "
    "collectives ('Multiprocess computations aren't implemented on "
    "the CPU backend'); the worker pins JAX_PLATFORMS=cpu, so the "
    "broadcast cannot run on this jaxlib regardless of host hardware. "
    "Strict so a jaxlib that restores it un-pins loudly. See "
    "FAILURES.md 'known test debt'.",
)
def test_multihost_two_process_broadcast(tmp_path):
    """The multihost helpers over a REAL two-process jax.distributed
    runtime (reference pattern: run the real thing small, SURVEY.md SS4):
    process 0's suggested configs reach process 1 via broadcast, and
    trial ids round-robin across hosts."""
    import socket
    import subprocess
    import sys
    import textwrap

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker_src = textwrap.dedent("""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        try:  # scrub a pre-latched tunnel plugin (private API; see conftest)
            from jax._src import xla_bridge as xb
            xb._backend_factories.pop("axon", None)
        except Exception:
            pass
        jax.config.update("jax_platforms", "cpu")
        pid, port = int(sys.argv[1]), sys.argv[2]
        from hyperopt_tpu.parallel import multihost
        multihost.initialize(f"127.0.0.1:{port}", num_processes=2,
                             process_id=pid)
        assert multihost.is_multihost()
        import numpy as np, jax.numpy as jnp
        if pid == 0:
            vals = jnp.arange(12.0).reshape(3, 4)
            act = jnp.ones((3, 4), bool)
        else:
            vals, act = jnp.zeros((3, 4)), jnp.zeros((3, 4), bool)
        v, a = multihost.broadcast_configs(vals, act)
        assert np.allclose(np.asarray(v), np.arange(12.0).reshape(3, 4))
        assert np.asarray(a).all()
        ids = multihost.shard_ids_for_host(list(range(10)))
        print(f"RESULT pid={pid} ids={ids}", flush=True)
    """)
    script = tmp_path / "mh_worker.py"
    script.write_text(worker_src)

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
    finally:  # never orphan a worker holding the coordinator port
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    assert "RESULT pid=0 ids=[0, 2, 4, 6, 8]" in outs[0]
    assert "RESULT pid=1 ids=[1, 3, 5, 7, 9]" in outs[1]


@pytest.mark.slow
@pytest.mark.xfail(
    strict=True,
    reason="same jaxlib 0.4.36 CPU-backend limitation as "
    "test_multihost_two_process_broadcast: the dcn_check workers run "
    "sharded_suggest collectives over a 2-process CPU runtime, which "
    "this jaxlib refuses. Strict so a capable jaxlib un-pins loudly. "
    "See FAILURES.md 'known test debt'.",
)
def test_two_process_dcn_sharded_suggest():
    """VERDICT r2 weak #6 + r3 weak #2: the FULL sharded surface executes
    across real process boundaries -- a 2-process x 4-device
    ``jax.distributed`` CPU runtime running (a) the public
    ``sharded_suggest`` API on a continuous space, (b) the same API on a
    MIXED space so the categorical EI sweep's hit-mask contraction and
    argmax-allgather cross DCN, (c) a population-sharded
    ``device_loop.compile_fmin`` whose trial axis spans both processes,
    (d, round 5) a fused ``compile_sha`` ladder whose rung populations
    and survivor gathers span both processes, matching the
    single-process ladder exactly, and (e, round 5) a fused
    ``compile_pbt`` schedule whose exploit-event rank/copy gathers move
    member state between processes, matching the single-process
    schedule exactly.  Agreement with the single-process path
    (two-sample KS per dim, n=256), loop determinism, and the
    sha/pbt-over-DCN exact-matches are asserted inside the process-0
    worker; this test asserts the run and its verdict line."""
    from hyperopt_tpu.parallel import dcn_check

    out = dcn_check.launch()
    assert "DCN RESULT procs=2 devices=8" in out, out[-2000:]
    assert "ks=" in out
    assert "mixed_ks=" in out
    assert "pop_sharded_loop={trial: 8}" in out
    assert "deterministic=True" in out
    assert "sha_dcn={trial: 8, n_configs: 8}" in out
    assert "sha_matches_unsharded=True" in out
    assert "sha_deterministic=True" in out
    assert "pbt_dcn={trial: 8, pop: 8}" in out
    assert "pbt_matches_unsharded=True" in out
    assert "pbt_deterministic=True" in out


def test_sharded_suggest_10k_candidates_nasbench():
    """BASELINE.json config #5 at its stated scale: the choice-heavy
    NAS-Bench space with >= 1024 candidates per device (8 devices ->
    10,240 total candidates per dim) through the sharded sweep.  Winners
    must be valid category indices and the draw must be non-degenerate."""
    from hyperopt_tpu.models import nasbench
    from hyperopt_tpu.base import Domain, JOB_STATE_DONE
    from hyperopt_tpu.jax_trials import obs_buffer_for, packed_space_for
    from hyperopt_tpu.parallel.sharded import build_sharded_suggest_fn
    from hyperopt_tpu import rand
    import jax

    domain = Domain(nasbench.objective, nasbench.space())
    trials = Trials()
    docs = rand.suggest(trials.new_trial_ids(40), domain, trials, seed=0)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
        cfg = {k: v[0] for k, v in doc["misc"]["vals"].items()}
        doc["result"] = {
            "status": "ok",
            "loss": nasbench.objective(
                {f"edge{e}": cfg[f"edge{e}"] for e in range(nasbench.N_EDGES)}
            ),
        }
    trials.insert_trial_docs(docs)
    trials.refresh()

    ps = packed_space_for(domain)
    buf = obs_buffer_for(domain, trials)
    mesh = default_mesh()  # all 8 virtual devices on the cand axis
    fn = build_sharded_suggest_fn(
        ps, mesh, n_cand_per_device=1280, gamma=0.25, lf=25.0,
        prior_weight=1.0,
    )
    values, active = jax.device_get(
        fn(jax.random.key(3), *buf.device_arrays(), batch=16)
    )
    assert values.shape == (ps.n_dims, 16)
    assert active.all()  # flat space: every dim active
    vals = np.round(values).astype(int)
    assert ((vals >= 0) & (vals < len(nasbench.OPS))).all()
    # non-degenerate: across 16 trials x 6 edges, more than one op drawn
    assert len(np.unique(vals)) > 1


def test_sharded_suggest_speculative():
    """speculative=k on the sharded path: one mesh-wide dispatch serves
    k sequential asks (same cache/staleness semantics as tpe_jax)."""
    from functools import partial

    from hyperopt_tpu.parallel import sharded_suggest

    trials = Trials()
    best = fmin(
        lambda x: (x - 3.0) ** 2,
        hp.uniform("x", -10, 10),
        algo=partial(sharded_suggest, speculative=4),
        max_evals=45,
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    assert len(trials) == 45
    assert trials.best_trial["result"]["loss"] < 2.5
    assert "x" in best


def test_sharded_speculative_auto_degrades_on_saturated_categorical():
    """The sharded path applies the same saturation guard, judged on the
    TOTAL categorical draw across the mesh: pure-categorical space with
    full option coverage -> speculation off, one-time warning, per-ask
    dispatch (VERDICT r2 weak #4)."""
    import warnings
    from functools import partial

    from hyperopt_tpu.base import Domain, JOB_STATE_DONE
    from hyperopt_tpu.models import nasbench
    from hyperopt_tpu.parallel import sharded_suggest
    from hyperopt_tpu import rand

    domain = Domain(nasbench.objective, nasbench.space())
    trials = Trials()
    docs = rand.suggest(trials.new_trial_ids(25), domain, trials, seed=0)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
        cfg = {k: v[0] for k, v in doc["misc"]["vals"].items()}
        doc["result"] = {"status": "ok", "loss": nasbench.objective(cfg)}
    trials.insert_trial_docs(docs)
    trials.refresh()

    algo = partial(sharded_suggest, speculative=8)
    out = []
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for i in range(3):
            (d,) = algo(trials.new_trial_ids(1), domain, trials, seed=50 + i)
            out.append(d["misc"]["vals"])
    msgs = [str(w.message) for w in caught if "speculative" in str(w.message)]
    assert len(msgs) == 1
    # parity with the non-speculative sharded path (same seeds/history)
    plain = []
    for i in range(3):
        (d,) = sharded_suggest(
            trials.new_trial_ids(1), domain, trials, seed=50 + i
        )
        plain.append(d["misc"]["vals"])
    assert out == plain


def test_sharded_step_has_one_collective():
    """Round-5 coalescing (VERDICT r4 weak #2): the compiled sharded
    suggest step must contain EXACTLY ONE collective -- a single
    all_gather of the packed (value, score) pairs -- not the
    per-(trial, dim)-class collectives GSPMD inserted when the
    cross-shard argmax lived outside the shard_map (round 4: 6
    all-gathers + 4 all-reduces per step)."""
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.models.synthetic import mixed_space
    from hyperopt_tpu.ops.compile import compile_space
    from hyperopt_tpu.parallel.sharded import (
        build_sharded_suggest_fn,
        per_device_count,
    )

    mesh = mesh_from_spec((8,), ("cand",))
    ps = compile_space(mixed_space())
    cap = 512
    fn = build_sharded_suggest_fn(
        ps, mesh, per_device_count(128, 8), 0.25, 25.0, 1.0,
        axis="cand", n_cand_cat_per_device=per_device_count(24, 8),
    )
    args = (
        jax.random.key(0), jnp.zeros((20, cap)),
        jnp.zeros((20, cap), bool), jnp.zeros((cap,)),
        jnp.zeros((cap,), bool),
    )
    txt = fn.lower(*args, batch=1).compile().as_text()
    # count INSTRUCTIONS, not substrings: newer XLA text dumps repeat
    # the instruction name at every operand-use site (`%all-gather.1`
    # inside fusion operands), so only the defining `op(` call site is
    # a collective
    assert txt.count("all-gather(") == 1, txt.count("all-gather(")
    for op in ("all-reduce(", "all-to-all(", "collective-permute("):
        assert txt.count(op) == 0, (op, txt.count(op))
