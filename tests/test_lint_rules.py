"""Fixture corpus: every rule's true-positive and near-miss behavior is
pinned by a bad/good file pair under tests/lint_fixtures/.

The *_bad.py file must produce at least one finding, all of the target
rule (a fixture that trips a neighboring rule is a fixture bug); the
*_good.py file -- the nearest legal idiom -- must produce none at all.
"""

import os

import pytest

from hyperopt_tpu.analysis.engine import lint_source

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")

PACK_RULES = [
    "GL101", "GL102", "GL103", "GL104",
    "GL201", "GL202", "GL203",
    "GL301", "GL302", "GL303", "GL304", "GL305", "GL306", "GL307",
    "GL308", "GL309",
]


def _fixture_path(rule_id, kind):
    stem = f"{rule_id.lower()}_{kind}.py"
    # GL302 is path-scoped to the fault domain, so its pair lives under
    # a distributed/ subdirectory (the path IS part of the fixture)
    sub = os.path.join(FIXTURES, "distributed", stem)
    return sub if os.path.exists(sub) else os.path.join(FIXTURES, stem)


def _lint(path):
    with open(path, encoding="utf-8") as f:
        source = f.read()
    findings, _ = lint_source(source, path=os.path.relpath(path))
    return findings


@pytest.mark.parametrize("rule_id", PACK_RULES)
def test_bad_fixture_trips_exactly_its_rule(rule_id):
    findings = _lint(_fixture_path(rule_id, "bad"))
    assert findings, f"{rule_id}: bad fixture produced no findings"
    assert {f.rule for f in findings} == {rule_id}, (
        f"{rule_id}: bad fixture tripped "
        f"{sorted({f.rule for f in findings})}"
    )


@pytest.mark.parametrize("rule_id", PACK_RULES)
def test_good_fixture_is_clean(rule_id):
    findings = _lint(_fixture_path(rule_id, "good"))
    assert not findings, (
        f"{rule_id}: near-miss fixture produced "
        f"{[(f.rule, f.line, f.message) for f in findings]}"
    )


def test_known_finding_counts():
    # multi-site fixtures pin the exact count, not just "some finding":
    # a rule that silently stops seeing one of the sites regresses here
    assert len(_lint(_fixture_path("GL101", "bad"))) == 3
    assert len(_lint(_fixture_path("GL202", "bad"))) == 2
    assert len(_lint(_fixture_path("GL304", "bad"))) == 2
    assert len(_lint(_fixture_path("GL305", "bad"))) == 2
    # two leaking attrs (latencies + trace), one finding per append
    # site; the rebound queue attr must contribute none
    assert len(_lint(_fixture_path("GL306", "bad"))) == 2
    # two hand-rolled counter bumps + one ad-hoc timing delta; the
    # underscore-private control attr must contribute none
    assert len(_lint(_fixture_path("GL307", "bad"))) == 3
    # one per-record fsync + one per-item durable_pickle; the barrier
    # helpers and the loop-defined closure must contribute none
    assert len(_lint(_fixture_path("GL308", "bad"))) == 2
    # a timeout-less create_connection, the makefile it feeds, and a
    # bare recv; the dial()/settimeout shapes must contribute none
    assert len(_lint(_fixture_path("GL309", "bad"))) == 3


def test_partial_wrapped_functions_resolve_as_jitted():
    # engine regression (PR 7): jit(partial(f, ...)) -- inline or via a
    # one-level `bound = partial(f); jit(bound)` alias -- must open f's
    # body as a jitted scope so GL101/GL102/GL201 see through the
    # wrapper; a partial never handed to a wrapper must not
    path = os.path.join(FIXTURES, "engine_partial_bad.py")
    findings = _lint(path)
    assert {f.rule for f in findings} == {"GL101"}
    assert len(findings) == 3  # np.asarray + float() in scorer, .item()
    assert not _lint(os.path.join(FIXTURES, "engine_partial_good.py"))


def test_findings_carry_location_and_hash():
    findings = _lint(_fixture_path("GL301", "bad"))
    (f,) = findings
    assert f.line > 0 and f.col >= 0
    assert "os.replace" in f.source_line
    assert len(f.content_hash()) == 40
    d = f.to_dict()
    assert d["rule"] == "GL301" and d["content_hash"] == f.content_hash()
