"""Unit tests for the TPE math (reference: tests/test_tpe.py, SURVEY.md SS4:
adaptive-parzen invariants, lpdfs validated against numerical integration
and empirical histograms, quantized mass sums to 1)."""

import numpy as np
import pytest

from hyperopt_tpu import tpe
from hyperopt_tpu.tpe import (
    GMM1,
    GMM1_lpdf,
    LGMM1,
    LGMM1_lpdf,
    adaptive_parzen_normal,
    categorical_posterior,
    linear_forgetting_weights,
)


# -- linear forgetting ------------------------------------------------------


def test_lfw_short_history_all_ones():
    np.testing.assert_array_equal(linear_forgetting_weights(10, 25), np.ones(10))


def test_lfw_long_history_ramps():
    w = linear_forgetting_weights(40, 25)
    assert len(w) == 40
    np.testing.assert_array_equal(w[-25:], np.ones(25))  # newest LF flat
    assert np.all(np.diff(w[:15]) >= 0)  # oldest ramp increasing
    assert w[0] == pytest.approx(1.0 / 40)


# -- adaptive parzen --------------------------------------------------------


def test_parzen_empty_obs_is_prior():
    w, mu, sigma = adaptive_parzen_normal([], 1.0, 0.0, 2.0)
    np.testing.assert_array_equal(w, [1.0])
    np.testing.assert_array_equal(mu, [0.0])
    np.testing.assert_array_equal(sigma, [2.0])


def test_parzen_component_count_and_normalization():
    obs = [0.1, -0.5, 1.2, 0.3]
    w, mu, sigma = adaptive_parzen_normal(obs, 1.0, 0.0, 5.0)
    assert len(w) == len(mu) == len(sigma) == len(obs) + 1
    assert w.sum() == pytest.approx(1.0)
    assert np.all(np.diff(mu) >= 0), "mus sorted"
    assert set(np.round(mu, 6)) == set(np.round(obs + [0.0], 6))


def test_parzen_sigma_clipping():
    prior_sigma = 4.0
    n = 10
    obs = np.linspace(-1, 1, n)
    w, mu, sigma = adaptive_parzen_normal(obs, 1.0, 0.0, prior_sigma)
    minsigma = prior_sigma / min(100.0, 1.0 + n)
    assert np.all(sigma <= prior_sigma + 1e-12)
    assert np.all(sigma >= minsigma - 1e-12)


def test_parzen_prior_sigma_pinned():
    obs = [3.0, 3.00001, 3.00002]
    prior_mu, prior_sigma = 0.0, 10.0
    w, mu, sigma = adaptive_parzen_normal(obs, 1.0, prior_mu, prior_sigma)
    prior_pos = int(np.argmin(np.abs(mu - prior_mu)))
    assert sigma[prior_pos] == prior_sigma


def test_parzen_concentrates_with_data():
    """More (tight) observations -> posterior mass concentrates near them."""
    rng = np.random.default_rng(0)
    obs = rng.normal(2.0, 0.1, size=30)
    w, mu, sigma = adaptive_parzen_normal(obs, 1.0, 0.0, 10.0)
    draws = GMM1(w, mu, sigma, rng=np.random.default_rng(1), size=(4000,))
    frac_near = np.mean(np.abs(draws - 2.0) < 1.0)
    assert frac_near > 0.8


# -- GMM sample / lpdf ------------------------------------------------------


def _numeric_integral(lpdf_fn, lo, hi, n=20001):
    xs = np.linspace(lo, hi, n)
    ys = np.exp(lpdf_fn(xs))
    return np.trapezoid(ys, xs)


def test_gmm1_lpdf_integrates_to_one():
    w = np.array([0.3, 0.7])
    mu = np.array([-1.0, 2.0])
    sigma = np.array([0.5, 1.5])
    total = _numeric_integral(lambda x: GMM1_lpdf(x, w, mu, sigma), -15, 15)
    assert total == pytest.approx(1.0, abs=1e-3)


def test_gmm1_lpdf_truncated_integrates_to_one():
    w = np.array([0.5, 0.5])
    mu = np.array([0.0, 3.0])
    sigma = np.array([1.0, 1.0])
    total = _numeric_integral(
        lambda x: GMM1_lpdf(x, w, mu, sigma, low=-1.0, high=4.0), -1.0, 4.0
    )
    assert total == pytest.approx(1.0, abs=1e-3)


def test_gmm1_samples_within_bounds_and_match_histogram():
    w = np.array([0.4, 0.6])
    mu = np.array([0.0, 5.0])
    sigma = np.array([1.0, 0.7])
    rng = np.random.default_rng(0)
    draws = GMM1(w, mu, sigma, low=-2.0, high=7.0, rng=rng, size=(20000,))
    assert draws.min() >= -2.0 and draws.max() <= 7.0
    # empirical histogram vs analytic density (survey SS4: validated against
    # empirical histograms of GMM1 draws)
    hist, edges = np.histogram(draws, bins=40, range=(-2, 7), density=True)
    centers = 0.5 * (edges[1:] + edges[:-1])
    dens = np.exp(GMM1_lpdf(centers, w, mu, sigma, low=-2.0, high=7.0))
    assert np.max(np.abs(hist - dens)) < 0.05


def test_gmm1_quantized_mass_sums_to_one():
    w = np.array([0.5, 0.5])
    mu = np.array([1.0, 8.0])
    sigma = np.array([2.0, 1.0])
    q = 1.0
    support = np.arange(0.0, 11.0, q)
    mass = np.exp(GMM1_lpdf(support, w, mu, sigma, low=0.0, high=10.0, q=q))
    assert mass.sum() == pytest.approx(1.0, abs=1e-6)


def test_gmm1_quantized_samples_on_grid():
    w = np.array([1.0])
    mu = np.array([5.0])
    sigma = np.array([3.0])
    draws = GMM1(w, mu, sigma, low=0.0, high=10.0, q=0.5,
                 rng=np.random.default_rng(1), size=(500,))
    np.testing.assert_allclose(draws, np.round(draws / 0.5) * 0.5)


def test_lgmm1_positive_and_lpdf_integrates():
    w = np.array([0.6, 0.4])
    mu = np.array([0.0, 1.0])  # log-space
    sigma = np.array([0.5, 0.3])
    rng = np.random.default_rng(2)
    draws = LGMM1(w, mu, sigma, rng=rng, size=(5000,))
    assert np.all(draws > 0)
    total = _numeric_integral(lambda x: LGMM1_lpdf(x, w, mu, sigma), 1e-4, 40.0)
    assert total == pytest.approx(1.0, abs=2e-3)


def test_lgmm1_truncated_bounds():
    w = np.array([1.0])
    mu = np.array([0.0])
    sigma = np.array([1.0])
    low, high = -1.0, 1.0  # log-space bounds
    draws = LGMM1(w, mu, sigma, low=low, high=high,
                  rng=np.random.default_rng(3), size=(2000,))
    assert draws.min() >= np.exp(low) - 1e-9
    assert draws.max() <= np.exp(high) + 1e-9


# -- categorical posterior --------------------------------------------------


def test_categorical_posterior_prior_only():
    p = categorical_posterior([], np.array([0.25, 0.25, 0.5]), 1.0, 25)
    np.testing.assert_allclose(p, [0.25, 0.25, 0.5])


def test_categorical_posterior_counts_dominate():
    obs = [2] * 50
    p = categorical_posterior(obs, np.ones(3) / 3, 1.0, 100)
    assert p[2] > 0.9
    assert p.sum() == pytest.approx(1.0)


def test_categorical_posterior_never_zero():
    p = categorical_posterior([0] * 100, np.ones(4) / 4, 1.0, 200)
    assert np.all(p > 0)


# -- suggest-level behavior -------------------------------------------------


def test_tpe_beats_random_on_quadratic():
    """Regression threshold (survey SS4): TPE > random on quadratic1."""
    import numpy as np
    from hyperopt_tpu import Trials, fmin, hp, rand

    def run(algo, seed):
        trials = Trials()
        fmin(
            lambda x: (x - 3.0) ** 2,
            hp.uniform("x", -10, 10),
            algo=algo,
            max_evals=75,
            trials=trials,
            rstate=np.random.default_rng(seed),
            show_progressbar=False,
        )
        return trials.best_trial["result"]["loss"]

    tpe_losses = [run(tpe.suggest, s) for s in range(3)]
    rand_losses = [run(rand.suggest, s) for s in range(3)]
    assert np.median(tpe_losses) <= np.median(rand_losses) + 1e-9
    assert np.median(tpe_losses) < 0.05


def test_tpe_startup_uses_prior():
    """Before n_startup_jobs, tpe must behave like random (seeded)."""
    from hyperopt_tpu import Domain, Trials, hp

    domain = Domain(lambda x: x, hp.uniform("x", 0, 1))
    trials = Trials()
    docs = tpe.suggest(trials.new_trial_ids(1), domain, trials, seed=42)
    assert len(docs) == 1
    v = docs[0]["misc"]["vals"]["x"][0]
    assert 0 <= v <= 1


def test_tpe_handles_failed_and_nan_trials():
    """ERROR/NaN trials must be masked out of the posterior (SURVEY.md SS5)."""
    import numpy as np
    from hyperopt_tpu import STATUS_FAIL, STATUS_OK, Trials, fmin, hp

    calls = {"n": 0}

    def sometimes_fails(x):
        calls["n"] += 1
        if calls["n"] % 4 == 0:
            return {"status": STATUS_FAIL}
        if calls["n"] % 7 == 0:
            return float("nan")
        return {"status": STATUS_OK, "loss": (x - 1) ** 2}

    trials = Trials()
    best = fmin(
        sometimes_fails,
        hp.uniform("x", -5, 5),
        algo=tpe.suggest,
        max_evals=40,
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    assert "x" in best
    assert trials.best_trial["result"]["loss"] >= 0


def test_obs_index_matches_reference_split_and_handles_late_completions():
    """The columnar _ObsIndex must reproduce ap_filter_trials +
    _obs_by_label EXACTLY (same (loss, tid) split, per-side tid order)
    on randomized stores with mixed states, and must ingest trials that
    complete after being scanned (the async-backend pattern)."""
    from hyperopt_tpu import Trials, rand
    from hyperopt_tpu.base import (
        Domain,
        JOB_STATE_DONE,
        JOB_STATE_ERROR,
        JOB_STATE_RUNNING,
    )
    from hyperopt_tpu.models.synthetic import (
        _many_dists_fn,
        _space_many_dists,
    )

    rng = np.random.default_rng(0)
    space = _space_many_dists()
    dom = Domain(_many_dists_fn, space)
    trials = Trials()
    docs = rand.suggest(trials.new_trial_ids(80), dom, trials, seed=0)
    for d in docs:
        r = rng.uniform()
        if r < 0.7:
            d["state"] = JOB_STATE_DONE
            d["result"] = {"status": "ok", "loss": float(rng.uniform(0, 10))}
        elif r < 0.8:
            d["state"] = JOB_STATE_RUNNING
        elif r < 0.9:
            d["state"] = JOB_STATE_ERROR
        else:
            d["state"] = JOB_STATE_DONE
            d["result"] = {"status": "ok", "loss": float("nan")}
    trials.insert_trial_docs(docs)
    trials.refresh()

    labels = sorted(tpe._domain_helper(dom).hps)
    for gamma, LF in ((0.25, 25), (0.15, 10)):
        below, above = tpe.ap_filter_trials(trials, gamma, LF)
        ref_b = tpe._obs_by_label(below, labels)
        ref_a = tpe._obs_by_label(above, labels)
        new_b, new_a = tpe._obs_index_for(dom, trials, labels).split_obs(
            gamma, LF
        )
        assert ref_b == new_b and ref_a == new_a

    # async pattern: RUNNING trials complete AFTER the index scanned them
    for d in trials._dynamic_trials:
        if d["state"] == JOB_STATE_RUNNING:
            d["state"] = JOB_STATE_DONE
            d["result"] = {"status": "ok", "loss": float(rng.uniform(0, 10))}
    trials.refresh()
    below, above = tpe.ap_filter_trials(trials, 0.25, 25)
    ref_b = tpe._obs_by_label(below, labels)
    new_b, _ = tpe._obs_index_for(dom, trials, labels).split_obs(0.25, 25)
    assert ref_b == new_b


def test_obs_index_keyed_by_trials_store():
    """Host-path twin of the device-buffer isolation contract: a Domain
    reused across stores must not mix observations."""
    from hyperopt_tpu import Trials, hp, rand
    from hyperopt_tpu.base import Domain, JOB_STATE_DONE

    dom = Domain(lambda x: x, hp.uniform("x", 0, 1))

    def store(n, loss):
        trials = Trials()
        docs = rand.suggest(trials.new_trial_ids(n), dom, trials, seed=n)
        trials.insert_trial_docs(docs)
        trials.refresh()
        for d in trials._dynamic_trials:
            d["state"] = JOB_STATE_DONE
            d["result"] = {"status": "ok", "loss": loss}
        trials.refresh()
        return trials

    a = store(4, 1.0)
    b = store(6, 2.0)
    idx_a = tpe._obs_index_for(dom, a, ["x"])
    assert len(idx_a.losses) == 4
    idx_b = tpe._obs_index_for(dom, b, ["x"])
    assert len(idx_b.losses) == 6 and set(idx_b.losses) == {2.0}
    # switching back re-keys again (fresh index, correct content)
    idx_a2 = tpe._obs_index_for(dom, a, ["x"])
    assert len(idx_a2.losses) == 4 and set(idx_a2.losses) == {1.0}
