"""graftpilot chaos (ISSUE 16): the self-driving fleet under fire.

The headline acceptance: a replica is killed mid-batch while the
autoscaler -- driven ONLY by the metrics the fleet already exposes,
no test back-channel -- executes a scale-out under a 10% transient
fault storm.  Zero lost / zero duplicate tells (live counters AND a
cold WAL-replay audit), every suggestion stream bitwise the same-seed
no-fault run's, the whole scenario replays bitwise, and the recorded
flight-recorder span log replays through the traffic harness to the
same streams bitwise.

Plus both PILOT crash windows (decision-to-actuation, mid-scale-out
migration) and the record-once-replay-bitwise harness on a solo
service.

Same discipline as ``tests/test_fleet_chaos.py``: seeded FaultPlans,
deterministic single-threaded pumping, protocol-client retries, and
every scenario run twice same-seed.
"""

import numpy as np
import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.distributed.faults import FaultPlan, SimulatedCrash
from hyperopt_tpu.obs.flightrec import FlightRecorder
from hyperopt_tpu.serve import (
    Fleet,
    FleetPilot,
    FleetRouter,
    HashRing,
    PilotConfig,
    SuggestService,
)
from hyperopt_tpu.serve.fleet import fleet_salt
from hyperopt_tpu.serve.replay import (
    ServiceTarget,
    load_workload,
    replay_fidelity,
    replay_workload,
    stream_hash,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _lockdep_armed(monkeypatch):
    from hyperopt_tpu.analysis import lockdep

    dep = lockdep.arm_scheduler_class(monkeypatch)
    yield dep
    assert dep.inversions == 0, dep.errors


SPACE = {
    "x": hp.uniform("x", -5, 5),
    "lr": hp.loguniform("lr", -5, 0),
    "c": hp.choice("c", [0, 1]),
}
ALGO_KW = dict(n_cand=16, n_cand_cat=8)
KW = dict(max_batch=8, n_startup_jobs=2, snapshot_cadence=4, **ALGO_KW)
REPLICAS = ("r0", "r1")
NAMES = tuple(f"s{i:02d}" for i in range(9))
R = 4  # tells per study the workload must end with, exactly


def loss_fn(vals):
    return (vals["x"]) ** 2 / 10 + abs(float(np.log(vals["lr"])) + 2) / 3


def victim_rid(name="s00"):
    ring = HashRing(REPLICAS, salt=fleet_salt("tpe", SPACE))
    return ring.owner(name)


def make_fleet(root, storm_rate=0.0, arm_victim=None, seed=0, fs=None,
               recorder=None):
    plans = {
        rid: FaultPlan(seed=seed * 100 + i, rate=storm_rate)
        for i, rid in enumerate(REPLICAS)
    }
    if arm_victim is not None:
        point, at = arm_victim
        plans[victim_rid()].arm(point, at=at)
    kw = dict(KW)
    if recorder is not None:
        kw["recorder"] = recorder
    return Fleet(
        SPACE, root, replica_ids=list(REPLICAS), plans=plans,
        fs=fs if fs is not None else FaultPlan(seed=seed).fs(), **kw,
    )


class Client:
    """The protocol client's retry discipline (test_fleet_chaos)."""

    def __init__(self, fleet):
        self.fleet = fleet
        self.router = FleetRouter(fleet)

    def _restart(self):
        self.router = FleetRouter(self.fleet)

    def create(self, name, seed):
        while True:
            try:
                return self.router.create_study(name, seed=seed)
            except SimulatedCrash:
                self._restart()

    def ask(self, name):
        recover = False
        while True:
            try:
                return self.router.ask(name, timeout=30, recover=recover)
            except SimulatedCrash:
                self._restart()
                recover = True

    def tell(self, name, tid, loss, vals):
        while True:
            try:
                return self.router.tell(name, tid, loss, vals=vals)
            except SimulatedCrash:
                self._restart()


def drive(client, streams, rounds, names=NAMES):
    for _ in range(rounds):
        for n in names:
            tid, vals = client.ask(n)
            client.tell(n, tid, loss_fn(vals), vals)
            streams[n].append((tid, tuple(sorted(vals.items()))))


def final_state(fleet, names=NAMES):
    out = {}
    for n in names:
        st = fleet.replicas[fleet.route(n)].service.scheduler.study(n)
        buf = st.buf
        out[n] = {
            "count": int(buf.count),
            "tids": buf.tids[: buf.count].tolist(),
            "losses": buf.losses[: buf.count].tolist(),
            "values": buf.values[:, : buf.count].copy(),
            "wal_total_tells": st.persist.wal.total_tells,
        }
    return out


def assert_zero_lost_zero_duplicate(state):
    for n, d in state.items():
        assert d["count"] == R, (n, d["count"])
        assert len(set(d["tids"])) == R, f"{n}: duplicate tid absorbed"
        assert d["wal_total_tells"] == R, (
            f"{n}: WAL logged {d['wal_total_tells']} tells for "
            f"{R} applied -- lost or duplicated"
        )


def assert_states_bitwise_equal(a, b, names=NAMES):
    for n in names:
        assert a[n]["tids"] == b[n]["tids"], n
        assert a[n]["losses"] == b[n]["losses"], n
        np.testing.assert_array_equal(a[n]["values"], b[n]["values"])
        assert a[n]["wal_total_tells"] == b[n]["wal_total_tells"]


def build_pressure(fleet, n_load=2, n_asks=2):
    """Queue real load the pilot can SEE: unregistered load studies
    opened directly on each live replica (few enough to fit the
    ``max_batch`` study cap next to the measured studies), several
    asks queued per study but not pumped -- ``serve_queue_depth`` in
    the next scrape is genuinely high.  Unregistered means they never
    migrate or fail over; their pending asks drain into later
    coalesced dispatches and none of them touch the measured studies'
    per-study suggestion streams."""
    futs = []
    for rid in sorted(fleet.replicas):
        rep = fleet.replicas[rid]
        if rep.dead or rep.partitioned:
            continue
        for j in range(n_load):
            name = f"zz-load-{rid}-{j}"
            if name not in rep.service.studies():
                rep.open_study(name, seed=900 + j)
            for _ in range(n_asks):
                futs.append(rep.ask_async(name))
    return futs


def pilot_for(fleet, **cfg_kw):
    """The production wiring: NO scrape override -- the controller's
    only input is ``fleet.metrics_rows`` (what /metrics serves)."""
    cfg = dict(
        min_replicas=2, max_replicas=3, queue_high=6.0, shed_high=0,
        breach_ticks=2, clear_ticks=50, cooldown_ticks=2,
    )
    cfg.update(cfg_kw)
    return FleetPilot(fleet, config=PilotConfig(**cfg))


@pytest.fixture(scope="module")
def clean_run(tmp_path_factory):
    """The same-seed NO-FAULT, NO-PILOT run every scenario's streams
    are pinned against (streams are placement-independent, so one
    2-replica clean run serves every membership trajectory)."""
    root = tmp_path_factory.mktemp("pilot-clean")
    fleet = make_fleet(str(root))
    client = Client(fleet)
    for i, n in enumerate(NAMES):
        client.create(n, seed=100 + i)
    streams = {n: [] for n in NAMES}
    drive(client, streams, R)
    state = final_state(fleet)
    fleet.shutdown()
    return streams, state


# ---------------------------------------------------------------------------
# THE acceptance scenario: kill-during-scale under a storm
# ---------------------------------------------------------------------------


def test_kill_during_autoscale_under_storm_acceptance(
    tmp_path, clean_run
):
    """A replica dies mid-batch in the dispatch window right after the
    autoscaler -- fed only by scraped metrics -- executed a scale-out,
    all under a 10% transient-errno storm.  Zero lost / zero duplicate
    tells (live AND cold-audited), every stream bitwise the no-fault
    run's, the scenario replays bitwise, and the recorded flight log
    replays through the traffic harness to the same streams bitwise."""
    clean_streams, clean_state = clean_run
    runs = []
    for rep in range(2):
        root = str(tmp_path / f"kill-{rep}")
        log = str(tmp_path / f"flight-{rep}.jsonl")
        recorder = FlightRecorder(path=log)
        fleet = make_fleet(
            root, storm_rate=0.10,
            arm_victim=("serve_mid_batch", 8), seed=7,
            recorder=recorder,
        )
        victim = victim_rid()
        pilot = pilot_for(fleet)
        assert pilot.scrape == fleet.metrics_rows  # no back-channel
        client = Client(fleet)
        for i, n in enumerate(NAMES):
            client.create(n, seed=100 + i)
        streams = {n: [] for n in NAMES}
        drive(client, streams, 1)
        assert pilot.tick().action == "hold"  # quiet warmup scrape

        # real pressure -> sustained breach -> the pilot scales out
        build_pressure(fleet)
        decisions = [pilot.tick(), pilot.tick()]
        assert [d.action for d in decisions] == ["hold", "scale_out"]
        assert decisions[1].rid == "p0" and "p0" in fleet.replicas
        assert not fleet.replicas[victim].dead

        # ...and the victim dies mid-batch in the very next dispatch
        # window, while the scaled-out fleet absorbs the storm
        drive(client, streams, R - 1)
        pilot.tick()  # the loop keeps running across the failover
        assert fleet.replicas[victim].dead
        assert victim not in fleet.ring.nodes
        assert fleet.recovery_ms is not None and fleet.recovery_ms > 0
        prows = {
            r["name"]: r for r in pilot.metrics_rows()
            if not r.get("labels")
        }
        assert prows["pilot_scale_outs_total"]["value"] == 1
        assert prows["pilot_scale_out_ms"]["value"] >= 0.0

        state = final_state(fleet)
        assert_zero_lost_zero_duplicate(state)
        fleet.shutdown()
        recorder.flush()

        # cold audit: re-materialize every study from nothing but its
        # WAL+bundle pair
        audit = SuggestService(
            SPACE, root=root, owner="audit", background=False,
            max_batch=16, n_startup_jobs=2, **ALGO_KW,
        )
        for n in NAMES:
            h = audit.create_study(n, takeover=True)
            assert h.n_tells == R, (n, h.n_tells)
        cold = {
            n: audit.scheduler.study(n).buf.tids[:R].tolist()
            for n in NAMES
        }
        audit.shutdown()
        for n in NAMES:
            assert cold[n] == state[n]["tids"], n

        # the flight log IS the workload: replay it against a fresh
        # solo service and the measured streams re-derive bitwise --
        # the faulted run's recovery re-serves collapse onto the
        # clean op order
        ops = load_workload(log)
        target = ServiceTarget(SuggestService(
            SPACE, background=False, max_batch=16, n_startup_jobs=2,
            **ALGO_KW,
        ))
        replayed = replay_workload(ops, target)
        target.service.shutdown()
        rep_named = {n: replayed[n] for n in NAMES}
        rec_named = {
            n: [(t, dict(v)) for t, v in streams[n]] for n in NAMES
        }
        assert replay_fidelity(rec_named, rep_named) == 1.0
        assert stream_hash(rep_named) == stream_hash(rec_named)
        runs.append((streams, state, ops,
                     [d.action for d in decisions]))

    # every stream bitwise the same-seed no-fault run's
    for streams, state, _, _ in runs:
        assert streams == clean_streams
        assert_states_bitwise_equal(state, clean_state)
    # the whole scenario -- streams, state, the extracted workload,
    # and the autoscaler's decision sequence -- replays bitwise
    assert runs[0][0] == runs[1][0]
    assert_states_bitwise_equal(runs[0][1], runs[1][1])
    assert runs[0][2] == runs[1][2]
    assert runs[0][3] == runs[1][3]


# ---------------------------------------------------------------------------
# the PILOT crash windows
# ---------------------------------------------------------------------------


def test_pilot_crash_between_decision_and_actuation(tmp_path, clean_run):
    """The pilot dies AFTER stamping its decision but BEFORE touching
    the fleet: nothing moved, and a restarted pilot -- decisions are
    stateless functions of the scrape -- re-derives the same decision
    from the same metrics and actuates it."""
    clean_streams, clean_state = clean_run
    root = str(tmp_path / "dw")
    fleet = make_fleet(root)
    client = Client(fleet)
    for i, n in enumerate(NAMES):
        client.create(n, seed=100 + i)
    streams = {n: [] for n in NAMES}
    drive(client, streams, 2)

    build_pressure(fleet)
    crashed = FleetPilot(
        fleet,
        config=PilotConfig(min_replicas=2, max_replicas=3,
                           queue_high=6.0, breach_ticks=1),
        fs=FaultPlan(seed=3).arm(
            "pilot_after_decision_before_actuate", at=1
        ).fs(),
    )
    with pytest.raises(SimulatedCrash):
        crashed.tick()
    # the decision was recorded, the fleet never moved
    assert crashed.metrics.counter("pilot_decisions_total").labels(
        action="scale_out"
    ).value == 1
    assert set(fleet.replicas) == set(REPLICAS)

    # restart: a fresh pilot re-scrapes, re-decides, actuates
    restarted = FleetPilot(
        fleet,
        config=PilotConfig(min_replicas=2, max_replicas=3,
                           queue_high=6.0, breach_ticks=1),
    )
    d = restarted.tick()
    assert d.action == "scale_out" and d.rid == "p0"
    assert "p0" in fleet.replicas

    drive(client, streams, R - 2)
    state = final_state(fleet)
    assert_zero_lost_zero_duplicate(state)
    assert streams == clean_streams
    assert_states_bitwise_equal(state, clean_state)
    fleet.shutdown()


def test_pilot_mid_scale_out_crash_heals_by_lazy_adoption(
    tmp_path, clean_run
):
    """The coordinator dies inside the pilot's scale-out after the
    FIRST remapped study migrated: the ring already includes the new
    replica, the remaining remapped studies are stranded behind it.
    The heal is the ordinary lazy-adoption path -- the new owner
    adopts each stranded study on its first routed request -- and
    re-running ``add_replica`` is refused, not the recovery."""
    clean_streams, clean_state = clean_run
    root = str(tmp_path / "ms")
    fleet = make_fleet(
        root, fs=FaultPlan(seed=4).arm("pilot_mid_scale_out", at=1).fs()
    )
    client = Client(fleet)
    for i, n in enumerate(NAMES):
        client.create(n, seed=100 + i)
    streams = {n: [] for n in NAMES}
    drive(client, streams, 2)

    build_pressure(fleet)
    pilot = pilot_for(fleet, breach_ticks=1)
    with pytest.raises(SimulatedCrash):
        pilot.tick()
    # the ring flipped, at most one study actually moved
    assert "p0" in fleet.replicas and "p0" in fleet.ring.nodes
    remapped = [n for n in NAMES if fleet.route(n) == "p0"]
    assert remapped, "the crash window needs a remapped share"
    resident = set(fleet.replicas["p0"].service.studies()) & set(NAMES)
    assert len(resident) == 1, resident
    stranded = [n for n in remapped if n not in resident]
    assert stranded, "nothing stranded -- the window closed too early"
    # re-running the actuation is refused; it is NOT the heal
    with pytest.raises(ValueError):
        fleet.add_replica("p0")

    # the heal: ordinary traffic -- the new owner lazily adopts each
    # stranded study on first contact
    drive(client, streams, R - 2)
    assert set(
        fleet.replicas["p0"].service.studies()
    ) & set(NAMES) >= set(remapped)
    state = final_state(fleet)
    assert_zero_lost_zero_duplicate(state)
    assert streams == clean_streams
    assert_states_bitwise_equal(state, clean_state)
    fleet.shutdown()


# ---------------------------------------------------------------------------
# record once, replay bitwise (the traffic harness, solo)
# ---------------------------------------------------------------------------


def test_flight_log_records_then_replays_bitwise(tmp_path):
    """Arm a flight recorder on a solo service, run a multi-study
    workload, then replay the span log against a FRESH service with a
    different batch shape: every suggestion stream re-derives bitwise
    (tid sequences checked by the harness, vals by hash)."""
    log = str(tmp_path / "flight.jsonl")
    svc = SuggestService(
        SPACE, background=False, max_batch=4, n_startup_jobs=2,
        recorder=FlightRecorder(path=log), **ALGO_KW,
    )
    handles = {
        f"m{i}": svc.create_study(f"m{i}", seed=30 + i) for i in range(3)
    }
    recorded = {n: [] for n in handles}
    for _ in range(3):
        for n, h in handles.items():
            tid, vals = h.ask()
            h.tell(tid, loss_fn(vals), vals=vals)
            recorded[n].append((tid, dict(vals)))
    svc.recorder.flush()
    svc.shutdown()

    target = ServiceTarget(SuggestService(
        SPACE, background=False, max_batch=16, n_startup_jobs=2,
        **ALGO_KW,
    ))
    replayed = replay_workload(load_workload(log), target)
    target.service.shutdown()
    assert replayed == recorded
    assert replay_fidelity(recorded, replayed) == 1.0
    # the hash is order-canonical, not dict-order-accidental
    assert stream_hash(dict(reversed(list(recorded.items())))) \
        == stream_hash(replayed)
