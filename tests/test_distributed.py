"""Distributed-backend tests: real subprocess workers over a temp file
queue (the reference pattern: no network mocks, spin up the real thing --
SURVEY.md SS4 'Distributed - Mongo' row), plus ThreadTrials."""

import os
import pickle
import subprocess
import sys
import time

import numpy as np
import pytest

from hyperopt_tpu import STATUS_OK, Trials, fmin, hp, rand, tpe
from hyperopt_tpu.base import JOB_STATE_DONE, JOB_STATE_ERROR
from hyperopt_tpu.distributed import FileJobQueue, FileTrials, ThreadTrials
from hyperopt_tpu.distributed.filequeue import worker_owner
from hyperopt_tpu.distributed.worker import run_one
from hyperopt_tpu.models.synthetic import DOMAINS


# ---------------------------------------------------------------------------
# FileJobQueue unit level
# ---------------------------------------------------------------------------


def make_doc(tid, exp_key=None):
    return {
        "tid": tid,
        "state": 0,
        "spec": None,
        "result": {"status": "new"},
        "misc": {"tid": tid, "cmd": None, "idxs": {"x": [tid]}, "vals": {"x": [0.5]}},
        "exp_key": exp_key,
        "owner": None,
        "version": 0,
        "book_time": None,
        "refresh_time": None,
    }


def test_queue_reserve_is_exclusive(tmp_path):
    q = FileJobQueue(str(tmp_path / "q"))
    q.publish(make_doc(0))
    d1 = q.reserve("w1")
    assert d1 is not None and d1["owner"] == "w1"
    assert q.reserve("w2") is None  # nothing left
    assert q.counts() == {"new": 0, "running": 1, "done": 0}


def test_queue_exp_key_filter(tmp_path):
    q = FileJobQueue(str(tmp_path / "q"))
    q.publish(make_doc(0, exp_key="A"))
    q.publish(make_doc(1, exp_key="B"))
    d = q.reserve("w", exp_key="B")
    assert d is not None and d["tid"] == 1


def test_queue_complete_and_reap(tmp_path):
    q = FileJobQueue(str(tmp_path / "q"))
    q.publish(make_doc(0))
    q.publish(make_doc(1))
    d0 = q.reserve("w1")
    d0["state"] = JOB_STATE_DONE
    d0["result"] = {"status": STATUS_OK, "loss": 1.0}
    q.complete(d0)
    assert q.counts() == {"new": 1, "running": 0, "done": 1}
    # a second reservation goes stale and is reaped back
    q.reserve("w-dead")
    assert q.counts()["running"] == 1
    time.sleep(0.05)
    assert q.reap(reserve_timeout=0.01) == 1
    assert q.counts() == {"new": 1, "running": 0, "done": 1}


def test_attachments_roundtrip(tmp_path):
    q = FileJobQueue(str(tmp_path / "q"))
    q.attachments["blob/with:odd chars"] = b"\x00\x01\x02"
    assert q.attachments["blob/with:odd chars"] == b"\x00\x01\x02"
    assert "blob/with:odd chars" in q.attachments
    del q.attachments["blob/with:odd chars"]
    assert "blob/with:odd chars" not in q.attachments
    with pytest.raises(KeyError):
        q.attachments["missing"]


# ---------------------------------------------------------------------------
# in-process worker (run_one)
# ---------------------------------------------------------------------------


def test_run_one_evaluates_job(tmp_path):
    from hyperopt_tpu.base import Domain

    dirpath = str(tmp_path / "q")
    trials = FileTrials(dirpath, reserve_timeout=None)
    domain = Domain(DOMAINS["quadratic1"].fn, DOMAINS["quadratic1"].make_space())
    trials.attachments["FMinIter_Domain"] = pickle.dumps(domain)
    docs = rand.suggest(trials.new_trial_ids(2), domain, trials, seed=0)
    trials.insert_trial_docs(docs)
    assert run_one(trials.queue, worker_owner())
    assert run_one(trials.queue, worker_owner())
    assert not run_one(trials.queue, worker_owner())  # queue drained
    trials.refresh()
    assert [t["state"] for t in trials.trials] == [JOB_STATE_DONE] * 2
    assert all(t["result"]["status"] == STATUS_OK for t in trials.trials)


def _exploding(x):
    raise RuntimeError("kaboom")


def test_run_one_captures_errors(tmp_path):
    from hyperopt_tpu.base import Domain

    exploding = _exploding
    dirpath = str(tmp_path / "q")
    trials = FileTrials(dirpath, reserve_timeout=None)
    domain = Domain(exploding, hp.uniform("x", 0, 1))
    trials.attachments["FMinIter_Domain"] = pickle.dumps(domain)
    docs = rand.suggest(trials.new_trial_ids(1), domain, trials, seed=0)
    trials.insert_trial_docs(docs)
    assert run_one(trials.queue, worker_owner())
    trials.refresh()
    t = trials.trials[0]
    assert t["state"] == JOB_STATE_ERROR
    assert "kaboom" in t["misc"]["error"][1]
    assert "RuntimeError" in t["misc"]["traceback"]


# ---------------------------------------------------------------------------
# full async fmin with real subprocess workers
# ---------------------------------------------------------------------------


def _spawn_worker(dirpath, extra=()):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [
            sys.executable, "-m", "hyperopt_tpu.distributed.worker",
            "--dir", dirpath, "--last-job-timeout", "30",
            "--poll-interval", "0.05", *extra,
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )


@pytest.mark.slow
def test_fmin_with_subprocess_workers(tmp_path):
    dirpath = str(tmp_path / "q")
    trials = FileTrials(dirpath, reserve_timeout=60.0)
    workers = [_spawn_worker(dirpath) for _ in range(2)]
    try:
        best = fmin(
            DOMAINS["quadratic1"].fn,
            DOMAINS["quadratic1"].make_space(),
            algo=tpe.suggest,
            max_evals=12,
            trials=trials,
            rstate=np.random.default_rng(0),
            show_progressbar=False,
            max_queue_len=4,
        )
    finally:
        for w in workers:
            w.terminate()
        for w in workers:
            w.wait(timeout=10)
    assert len(trials) == 12
    assert all(t["state"] == JOB_STATE_DONE for t in trials.trials)
    assert "x" in best
    # results were computed by the worker processes, not this one
    owners = {t["owner"] for t in trials.trials}
    assert all(o and ":" in o for o in owners)
    pids = {int(o.split(":")[1]) for o in owners}
    assert os.getpid() not in pids


@pytest.mark.slow
def test_filetrials_resume_across_instances(tmp_path):
    """The queue directory IS the experiment state (DB-as-state parity)."""
    from hyperopt_tpu.base import Domain

    dirpath = str(tmp_path / "q")
    trials = FileTrials(dirpath, reserve_timeout=None)
    domain = Domain(DOMAINS["quadratic1"].fn, DOMAINS["quadratic1"].make_space())
    trials.attachments["FMinIter_Domain"] = pickle.dumps(domain)
    docs = rand.suggest(trials.new_trial_ids(3), domain, trials, seed=1)
    trials.insert_trial_docs(docs)
    while run_one(trials.queue, worker_owner()):
        pass
    blob = pickle.dumps(trials)
    revived = pickle.loads(blob)
    revived.refresh()
    assert len(revived) == 3
    assert all(t["state"] == JOB_STATE_DONE for t in revived.trials)


# ---------------------------------------------------------------------------
# ThreadTrials
# ---------------------------------------------------------------------------


def test_thread_trials_parallel_evaluation():
    calls = []

    def slow_quad(x):
        calls.append(time.time())
        time.sleep(0.15)
        return (x - 3.0) ** 2

    trials = ThreadTrials(parallelism=4)
    t0 = time.time()
    best = fmin(
        slow_quad, hp.uniform("x", -10, 10), algo=rand.suggest,
        max_evals=8, trials=trials, rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    wall = time.time() - t0
    assert len(trials) == 8
    assert all(t["state"] == JOB_STATE_DONE for t in trials.trials)
    assert wall < 8 * 0.15  # beat the serial time => threads overlapped
    assert "x" in best


def test_thread_trials_error_capture():
    def flaky(x):
        if x > 0:
            raise ValueError("positive!")
        return x

    trials = ThreadTrials(parallelism=2)
    fmin(
        flaky, hp.uniform("x", -1, 1), algo=rand.suggest, max_evals=10,
        trials=trials, rstate=np.random.default_rng(3),
        show_progressbar=False, return_argmin=False,
    )
    states = {t["state"] for t in trials.trials}
    assert JOB_STATE_DONE in states and JOB_STATE_ERROR in states


def test_thread_trials_timeout_cancels_queue():
    def slow(x):
        time.sleep(0.1)
        return x

    trials = ThreadTrials(parallelism=1, timeout=0.5)
    fmin(
        slow, hp.uniform("x", 0, 1), algo=rand.suggest, max_evals=1000,
        trials=trials, rstate=np.random.default_rng(0),
        show_progressbar=False, return_argmin=False,
    )
    assert len(trials) < 1000
    assert trials._fmin_cancelled or len(trials) < 20
