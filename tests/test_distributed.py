"""Distributed-backend tests: real subprocess workers over a temp file
queue (the reference pattern: no network mocks, spin up the real thing --
SURVEY.md SS4 'Distributed - Mongo' row), plus ThreadTrials."""

import os
import pickle
import subprocess
import sys
import time

import numpy as np
import pytest

from hyperopt_tpu import STATUS_OK, Trials, fmin, hp, rand, tpe
from hyperopt_tpu.base import JOB_STATE_DONE, JOB_STATE_ERROR
from hyperopt_tpu.distributed import FileJobQueue, FileTrials, ThreadTrials
from hyperopt_tpu.distributed.filequeue import worker_owner
from hyperopt_tpu.distributed.worker import run_one
from hyperopt_tpu.models.synthetic import DOMAINS


# ---------------------------------------------------------------------------
# FileJobQueue unit level
# ---------------------------------------------------------------------------


def make_doc(tid, exp_key=None):
    return {
        "tid": tid,
        "state": 0,
        "spec": None,
        "result": {"status": "new"},
        "misc": {"tid": tid, "cmd": None, "idxs": {"x": [tid]}, "vals": {"x": [0.5]}},
        "exp_key": exp_key,
        "owner": None,
        "version": 0,
        "book_time": None,
        "refresh_time": None,
    }


def test_queue_reserve_is_exclusive(tmp_path):
    q = FileJobQueue(str(tmp_path / "q"))
    q.publish(make_doc(0))
    d1 = q.reserve("w1")
    assert d1 is not None and d1["owner"] == "w1"
    assert q.reserve("w2") is None  # nothing left
    assert q.counts() == {"new": 0, "running": 1, "done": 0}


def test_queue_exp_key_filter(tmp_path):
    q = FileJobQueue(str(tmp_path / "q"))
    q.publish(make_doc(0, exp_key="A"))
    q.publish(make_doc(1, exp_key="B"))
    d = q.reserve("w", exp_key="B")
    assert d is not None and d["tid"] == 1


def test_queue_complete_and_reap(tmp_path):
    q = FileJobQueue(str(tmp_path / "q"))
    q.publish(make_doc(0))
    q.publish(make_doc(1))
    d0 = q.reserve("w1")
    d0["state"] = JOB_STATE_DONE
    d0["result"] = {"status": STATUS_OK, "loss": 1.0}
    q.complete(d0)
    assert q.counts() == {"new": 1, "running": 0, "done": 1}
    # a second reservation goes stale and is reaped back
    q.reserve("w-dead")
    assert q.counts()["running"] == 1
    time.sleep(0.05)
    assert q.reap(reserve_timeout=0.01) == 1
    assert q.counts() == {"new": 1, "running": 0, "done": 1}


def test_queue_reserve_refreshes_stale_mtime_before_rename(tmp_path):
    """ADVICE r5: a job that waited in new/ longer than reserve_timeout
    must NOT carry its stale mtime through the CAS rename into running/
    -- in the window before _write_atomic rewrites the claim, a
    concurrent reaper would see an already-expired RUNNING file and
    recycle the live claim (duplicated evaluation).  The _write_atomic
    rewrite is stubbed out to hold the window open, so the test sees
    exactly the mtime the rename carried."""
    from hyperopt_tpu.distributed import filequeue

    q = FileJobQueue(str(tmp_path / "q"))
    q.publish(make_doc(0))
    src = os.path.join(str(tmp_path / "q"), "new", "0.json")
    stale = time.time() - 3600  # waited an hour in new/
    os.utime(src, (stale, stale))

    real_write = filequeue._write_atomic
    try:
        filequeue._write_atomic = lambda path, doc, **kw: None  # hold the window
        d = q.reserve("w1")
    finally:
        filequeue._write_atomic = real_write
    assert d is not None and d["tid"] == 0
    dst = os.path.join(str(tmp_path / "q"), "running", "0.json")
    # the rename itself carried a fresh claim timestamp
    assert time.time() - os.path.getmtime(dst) < 60
    # and a reaper inside the window leaves the live claim alone
    assert q.reap(reserve_timeout=120) == 0
    assert q.counts()["running"] == 1


def test_attachments_roundtrip(tmp_path):
    q = FileJobQueue(str(tmp_path / "q"))
    q.attachments["blob/with:odd chars"] = b"\x00\x01\x02"
    assert q.attachments["blob/with:odd chars"] == b"\x00\x01\x02"
    assert "blob/with:odd chars" in q.attachments
    del q.attachments["blob/with:odd chars"]
    assert "blob/with:odd chars" not in q.attachments
    with pytest.raises(KeyError):
        q.attachments["missing"]


# ---------------------------------------------------------------------------
# in-process worker (run_one)
# ---------------------------------------------------------------------------


def test_run_one_evaluates_job(tmp_path):
    from hyperopt_tpu.base import Domain

    dirpath = str(tmp_path / "q")
    trials = FileTrials(dirpath, reserve_timeout=None)
    domain = Domain(DOMAINS["quadratic1"].fn, DOMAINS["quadratic1"].make_space())
    trials.attachments["FMinIter_Domain"] = pickle.dumps(domain)
    docs = rand.suggest(trials.new_trial_ids(2), domain, trials, seed=0)
    trials.insert_trial_docs(docs)
    assert run_one(trials.queue, worker_owner())
    assert run_one(trials.queue, worker_owner())
    assert not run_one(trials.queue, worker_owner())  # queue drained
    trials.refresh()
    assert [t["state"] for t in trials.trials] == [JOB_STATE_DONE] * 2
    assert all(t["result"]["status"] == STATUS_OK for t in trials.trials)


def _exploding(x):
    raise RuntimeError("kaboom")


def test_run_one_captures_errors(tmp_path):
    from hyperopt_tpu.base import Domain

    exploding = _exploding
    dirpath = str(tmp_path / "q")
    trials = FileTrials(dirpath, reserve_timeout=None)
    domain = Domain(exploding, hp.uniform("x", 0, 1))
    trials.attachments["FMinIter_Domain"] = pickle.dumps(domain)
    docs = rand.suggest(trials.new_trial_ids(1), domain, trials, seed=0)
    trials.insert_trial_docs(docs)
    assert run_one(trials.queue, worker_owner())
    trials.refresh()
    t = trials.trials[0]
    assert t["state"] == JOB_STATE_ERROR
    assert "kaboom" in t["misc"]["error"][1]
    assert "RuntimeError" in t["misc"]["traceback"]


# ---------------------------------------------------------------------------
# full async fmin with real subprocess workers
# ---------------------------------------------------------------------------


def _spawn_worker(dirpath, extra=()):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [
            sys.executable, "-m", "hyperopt_tpu.distributed.worker",
            "--dir", dirpath, "--last-job-timeout", "30",
            "--poll-interval", "0.05", *extra,
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )


@pytest.mark.slow
def test_fmin_with_subprocess_workers(tmp_path):
    dirpath = str(tmp_path / "q")
    trials = FileTrials(dirpath, reserve_timeout=60.0)
    workers = [_spawn_worker(dirpath) for _ in range(2)]
    try:
        best = fmin(
            DOMAINS["quadratic1"].fn,
            DOMAINS["quadratic1"].make_space(),
            algo=tpe.suggest,
            max_evals=12,
            trials=trials,
            rstate=np.random.default_rng(0),
            show_progressbar=False,
            max_queue_len=4,
        )
    finally:
        for w in workers:
            w.terminate()
        for w in workers:
            w.wait(timeout=10)
    assert len(trials) == 12
    assert all(t["state"] == JOB_STATE_DONE for t in trials.trials)
    assert "x" in best
    # results were computed by the worker processes, not this one
    owners = {t["owner"] for t in trials.trials}
    assert all(o and ":" in o for o in owners)
    pids = {int(o.split(":")[1]) for o in owners}
    assert os.getpid() not in pids


def _objective_a(x):
    return 10.0 + x


def _objective_b(x):
    return 20.0 + x


def test_worker_reloads_republished_domain(tmp_path):
    """A long-lived worker must pick up a RE-published Domain (a new
    driver reusing the queue directory), not evaluate the stale cached
    one forever -- the cache is keyed by the attachment's mtime."""
    from hyperopt_tpu.base import Domain

    dirpath = str(tmp_path / "q")
    trials = FileTrials(dirpath, reserve_timeout=None)
    space = hp.uniform("x", 0, 1)
    trials.attachments["FMinIter_Domain"] = pickle.dumps(
        Domain(_objective_a, space)
    )
    docs = rand.suggest(trials.new_trial_ids(1), Domain(_objective_a, space),
                        trials, seed=0)
    trials.insert_trial_docs(docs)
    assert run_one(trials.queue, worker_owner())
    time.sleep(0.02)  # distinct attachment mtime_ns
    trials.attachments["FMinIter_Domain"] = pickle.dumps(
        Domain(_objective_b, space)
    )
    docs = rand.suggest(trials.new_trial_ids(1), Domain(_objective_b, space),
                        trials, seed=1)
    trials.insert_trial_docs(docs)
    assert run_one(trials.queue, worker_owner())
    trials.refresh()
    losses = sorted(t["result"]["loss"] for t in trials.trials)
    assert 10.0 <= losses[0] < 11.0  # first domain
    assert 20.0 <= losses[1] < 21.0  # re-published domain, same worker cache


def _slow_objective(x):
    time.sleep(0.6)
    return x


def test_worker_gives_back_job_when_domain_missing(tmp_path):
    """A worker that cannot load the doc's named Domain must give the
    reserved job BACK to new/ (not strand it in running/ or mark it
    failed) and surface the error -- another worker can still run it."""
    from hyperopt_tpu.distributed.worker import WorkerExit

    dirpath = str(tmp_path / "q")
    q = FileJobQueue(dirpath)
    doc = make_doc(0)
    doc["misc"]["cmd"] = ("domain_attachment", "FMinIter_Domain.asha-dead")
    q.publish(doc)
    with pytest.raises(WorkerExit, match="asha-dead") as exc:
        run_one(q, worker_owner())
    assert exc.value.failed_tid == 0  # the CLI cools this tid down
    assert q.counts() == {"new": 1, "running": 0, "done": 0}
    assert not q.done_docs()  # and it was NOT marked failed
    # a worker excluding the poisoned tid skips it (no livelock on the
    # sorted scan) ...
    assert not run_one(q, worker_owner(), exclude_tids=[0])
    # ... while an unexcluded reserver can still claim it, tid intact
    back = q.reserve("w2")
    assert back is not None and back["tid"] == 0


def test_worker_resolves_domain_per_doc_cmd(tmp_path):
    """Two drivers sharing one queue directory: each doc's cmd names
    its own Domain attachment, so a worker evaluates every job with
    the right objective (no clobbering)."""
    from hyperopt_tpu.base import Domain

    dirpath = str(tmp_path / "q")
    q = FileJobQueue(dirpath)
    space = hp.uniform("x", 0, 1)
    q.attachments["FMinIter_Domain"] = pickle.dumps(
        Domain(_objective_a, space)
    )
    q.attachments["FMinIter_Domain.asha-x1"] = pickle.dumps(
        Domain(_objective_b, space)
    )
    for tid, key in ((0, "FMinIter_Domain"), (1, "FMinIter_Domain.asha-x1")):
        doc = make_doc(0)
        doc["tid"] = doc["misc"]["tid"] = tid
        doc["misc"]["cmd"] = ("domain_attachment", key)
        doc["misc"]["idxs"] = {"x": [tid]}
        doc["misc"]["vals"] = {"x": [0.5]}
        q.publish(doc)
    assert run_one(q, worker_owner())
    assert run_one(q, worker_owner())
    done = q.done_docs()
    assert 10.0 <= done[0]["result"]["loss"] < 11.0  # _objective_a
    assert 20.0 <= done[1]["result"]["loss"] < 21.0  # _objective_b


def test_worker_heartbeat_defeats_reaping_of_live_jobs(tmp_path):
    """An evaluation LONGER than the reserve timeout keeps its claim:
    the heartbeat refreshes the running-file mtime, so reap() recycles
    only genuinely dead workers' jobs (no duplicate evaluation of slow
    objectives)."""
    import threading

    from hyperopt_tpu.base import Domain

    dirpath = str(tmp_path / "q")
    trials = FileTrials(dirpath, reserve_timeout=None)
    space = hp.uniform("x", 0, 1)
    trials.attachments["FMinIter_Domain"] = pickle.dumps(
        Domain(_slow_objective, space)
    )
    docs = rand.suggest(
        trials.new_trial_ids(1), Domain(_slow_objective, space), trials,
        seed=0,
    )
    trials.insert_trial_docs(docs)
    t = threading.Thread(
        target=run_one,
        args=(trials.queue, worker_owner()),
        kwargs={"heartbeat": 0.05},
    )
    t.start()
    time.sleep(0.35)  # well past a 0.15s reserve timeout, eval still going
    assert trials.queue.reap(reserve_timeout=0.15) == 0  # claim is alive
    t.join(timeout=10)
    assert trials.queue.counts() == {"new": 0, "running": 0, "done": 1}


# ---------------------------------------------------------------------------
# ASHA over the filequeue (async scheduler x async backend)
# ---------------------------------------------------------------------------


def test_budgeted_domain_fn_worker_roundtrip(tmp_path):
    """Worker-side budget plumbing: a queued doc carrying
    misc['budget'] evaluates fn(config, budget) through the pickled
    BudgetedDomainFn -- the in-process run_one twin of the subprocess
    test below."""
    from hyperopt_tpu.base import Domain
    from hyperopt_tpu.distributed.asha_queue import BudgetedDomainFn
    from hyperopt_tpu.models.synthetic import (
        budgeted_quadratic_fn, budgeted_quadratic_space,
    )

    dirpath = str(tmp_path / "q")
    q = FileJobQueue(dirpath)
    domain = Domain(
        BudgetedDomainFn(budgeted_quadratic_fn), budgeted_quadratic_space()
    )
    q.attachments["FMinIter_Domain"] = pickle.dumps(domain)
    for tid, budget in (("t0", 1), ("t1", 9)):
        doc = make_doc(0)
        doc["tid"] = doc["misc"]["tid"] = tid
        doc["misc"]["cmd"] = ("domain_attachment", "FMinIter_Domain")
        doc["misc"]["idxs"] = {"x": [tid]}
        doc["misc"]["vals"] = {"x": [0.5]}
        doc["misc"]["budget"] = budget
        q.publish(doc)
    assert run_one(q, worker_owner())
    assert run_one(q, worker_owner())
    done = q.done_docs()
    for tid, budget in (("t0", 1), ("t1", 9)):
        want = budgeted_quadratic_fn({"x": 0.5}, budget)
        assert done[tid]["result"]["loss"] == pytest.approx(want)
    # the two budgets produced different losses: budget reached the fn
    assert done["t0"]["result"]["loss"] != done["t1"]["result"]["loss"]


@pytest.mark.slow
def test_asha_filequeue_with_subprocess_workers(tmp_path):
    """The async scheduler drives the async backend: ASHA promotion
    decisions on the driver, evaluations farmed to real worker
    SUBPROCESSES through the queue's atomic reservation.  Ladder
    invariants hold and every result was computed out-of-process."""
    from hyperopt_tpu.distributed import asha_filequeue
    from hyperopt_tpu.models.synthetic import (
        budgeted_quadratic_fn, budgeted_quadratic_space,
    )

    dirpath = str(tmp_path / "q")
    workers = [_spawn_worker(dirpath) for _ in range(2)]
    try:
        out = asha_filequeue(
            budgeted_quadratic_fn, budgeted_quadratic_space(),
            max_budget=9, dirpath=dirpath, eta=3, max_jobs=30,
            inflight=4, rstate=np.random.default_rng(0),
            eval_timeout=120.0,
        )
    finally:
        for w in workers:
            w.terminate()
        for w in workers:
            w.wait(timeout=10)
    trials = out["trials"]
    assert len(trials) == 30
    budgets = [t["result"]["budget"] for t in trials.trials]
    assert set(budgets) <= {1, 3, 9}
    assert budgets.count(1) > budgets.count(9) > 0
    # promotion chain: every deeper-rung x was first seen at the rung below
    x_at = lambda b: {
        round(t["misc"]["vals"]["x"][0], 9)
        for t in trials.trials if t["result"]["budget"] == b
    }
    assert x_at(3) <= x_at(1) and x_at(9) <= x_at(3)
    assert np.isfinite(out["best_loss"])
    # transport record: every queue job completed by a WORKER process
    q = FileJobQueue(dirpath)
    done = q.done_docs()
    assert len(done) == 30
    owners = {d["owner"] for d in done.values()}
    assert owners and all(o and ":" in o for o in owners)
    assert os.getpid() not in {int(o.split(":")[1]) for o in owners}
    # every queue doc carried its rung budget to the worker
    assert {d["misc"]["budget"] for d in done.values()} <= {1, 3, 9}


def test_asha_filequeue_rejects_queue_backed_trials(tmp_path):
    """Passing a FileTrials as the scheduler store would re-publish
    every recorded doc into new/ as a budget-less job -- refused."""
    from hyperopt_tpu.distributed import asha_filequeue
    from hyperopt_tpu.models.synthetic import (
        budgeted_quadratic_fn, budgeted_quadratic_space,
    )

    with pytest.raises(ValueError, match="in-memory Trials"):
        asha_filequeue(
            budgeted_quadratic_fn, budgeted_quadratic_space(),
            max_budget=4, dirpath=str(tmp_path / "q"),
            trials=FileTrials(str(tmp_path / "q2"), reserve_timeout=None),
        )


def test_asha_filequeue_no_workers_times_out(tmp_path):
    """With nobody serving the queue, every evaluation expires into a
    failed trial and the scheduler raises AllTrialsFailed rather than
    hanging forever."""
    from hyperopt_tpu.distributed import asha_filequeue
    from hyperopt_tpu.exceptions import AllTrialsFailed
    from hyperopt_tpu.models.synthetic import (
        budgeted_quadratic_fn, budgeted_quadratic_space,
    )

    with pytest.raises(AllTrialsFailed):
        asha_filequeue(
            budgeted_quadratic_fn, budgeted_quadratic_space(),
            max_budget=4, dirpath=str(tmp_path / "q"), eta=2, max_jobs=4,
            inflight=2, rstate=np.random.default_rng(0),
            eval_timeout=0.3, poll_interval=0.02,
        )


@pytest.mark.slow
def test_asha_filequeue_driver_kill_resume(tmp_path):
    """SIGKILL the DRIVER process mid-run (workers stay alive), then
    resume from its checkpoint in a fresh driver: the run completes to
    the exact total budget over the same worker pool -- the
    checkpoint x transport composition the module docstring claims."""
    import signal

    dirpath = str(tmp_path / "q")
    ckpt = str(tmp_path / "asha.ckpt")
    # ONE kwargs dict: the killed driver's code string and the resume
    # call must not drift apart (the guard only catches some fields)
    kw = dict(
        max_budget=9, eta=3, max_jobs=60, inflight=2,
        dirpath=dirpath, checkpoint=ckpt, eval_timeout=120.0,
    )
    code = (
        "import numpy as np\n"
        "from hyperopt_tpu.distributed import asha_filequeue\n"
        "from hyperopt_tpu.models.synthetic import (\n"
        "    budgeted_quadratic_fn, budgeted_quadratic_space)\n"
        "asha_filequeue(budgeted_quadratic_fn, budgeted_quadratic_space(),\n"
        f"    rstate=np.random.default_rng(3), **{kw!r})\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    workers = [_spawn_worker(dirpath) for _ in range(2)]
    drv = None
    drv_err = open(str(tmp_path / "driver.stderr"), "w+")
    try:
        # stderr to a FILE, not a pipe: an undrained pipe would block a
        # chatty driver at ~64KB and masquerade as a worker stall
        drv = subprocess.Popen(
            [sys.executable, "-c", code], env=env,
            stdout=subprocess.DEVNULL, stderr=drv_err,
        )
        q = FileJobQueue(dirpath)
        deadline = time.time() + 300
        while time.time() < deadline and len(q.done_docs()) < 8:
            if drv.poll() is not None:  # driver crashed at startup:
                # fail with ITS error, not a misleading worker blame
                drv_err.seek(0)
                raise AssertionError(
                    f"driver exited rc={drv.returncode}: "
                    f"{drv_err.read()[-2000:]}"
                )
            time.sleep(0.1)
        assert len(q.done_docs()) >= 8, "workers never progressed"
        drv.send_signal(signal.SIGKILL)  # a real kill, not an exception
        drv.wait(timeout=10)
        assert os.path.exists(ckpt), "no snapshot survived the kill"
        # the kill must land MID-run, else resume has nothing to do and
        # this test silently stops covering its subject (60 jobs at
        # >=10ms each vs a signal in-flight for ms makes this robust)
        from hyperopt_tpu.utils.checkpoint import load_trials

        assert load_trials(ckpt)["recorded"] < 60, (
            "driver finished before the kill; raise max_jobs"
        )

        from hyperopt_tpu.distributed import asha_filequeue
        from hyperopt_tpu.models.synthetic import (
            budgeted_quadratic_fn, budgeted_quadratic_space,
        )

        out = asha_filequeue(
            budgeted_quadratic_fn, budgeted_quadratic_space(),
            rstate=np.random.default_rng(3), **kw,
        )
    finally:
        if drv is not None and drv.poll() is None:
            drv.kill()  # never leak a driver past a failed assertion
            drv.wait(timeout=10)
        drv_err.close()
        for w in workers:
            w.terminate()
        for w in workers:
            w.wait(timeout=10)
    trials = out["trials"]
    assert len(trials) == 60  # exact total budget across kill + resume
    budgets = [t["result"]["budget"] for t in trials.trials]
    assert set(budgets) <= {1, 3, 9}
    x_at = lambda b: {
        round(t["misc"]["vals"]["x"][0], 9)
        for t in trials.trials if t["result"]["budget"] == b
    }
    assert x_at(3) <= x_at(1) and x_at(9) <= x_at(3)
    assert np.isfinite(out["best_loss"])


@pytest.mark.slow
def test_filetrials_resume_across_instances(tmp_path):
    """The queue directory IS the experiment state (DB-as-state parity)."""
    from hyperopt_tpu.base import Domain

    dirpath = str(tmp_path / "q")
    trials = FileTrials(dirpath, reserve_timeout=None)
    domain = Domain(DOMAINS["quadratic1"].fn, DOMAINS["quadratic1"].make_space())
    trials.attachments["FMinIter_Domain"] = pickle.dumps(domain)
    docs = rand.suggest(trials.new_trial_ids(3), domain, trials, seed=1)
    trials.insert_trial_docs(docs)
    while run_one(trials.queue, worker_owner()):
        pass
    blob = pickle.dumps(trials)
    revived = pickle.loads(blob)
    revived.refresh()
    assert len(revived) == 3
    assert all(t["state"] == JOB_STATE_DONE for t in revived.trials)


# ---------------------------------------------------------------------------
# ThreadTrials
# ---------------------------------------------------------------------------


def test_thread_trials_parallel_evaluation():
    calls = []

    def slow_quad(x):
        calls.append(time.time())
        time.sleep(0.15)
        return (x - 3.0) ** 2

    trials = ThreadTrials(parallelism=4)
    t0 = time.time()
    best = fmin(
        slow_quad, hp.uniform("x", -10, 10), algo=rand.suggest,
        max_evals=8, trials=trials, rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    wall = time.time() - t0
    assert len(trials) == 8
    assert all(t["state"] == JOB_STATE_DONE for t in trials.trials)
    assert wall < 8 * 0.15  # beat the serial time => threads overlapped
    assert "x" in best


def test_thread_trials_error_capture():
    def flaky(x):
        if x > 0:
            raise ValueError("positive!")
        return x

    trials = ThreadTrials(parallelism=2)
    fmin(
        flaky, hp.uniform("x", -1, 1), algo=rand.suggest, max_evals=10,
        trials=trials, rstate=np.random.default_rng(3),
        show_progressbar=False, return_argmin=False,
    )
    states = {t["state"] for t in trials.trials}
    assert JOB_STATE_DONE in states and JOB_STATE_ERROR in states


def test_thread_trials_timeout_cancels_queue():
    def slow(x):
        time.sleep(0.1)
        return x

    trials = ThreadTrials(parallelism=1, timeout=0.5)
    fmin(
        slow, hp.uniform("x", 0, 1), algo=rand.suggest, max_evals=1000,
        trials=trials, rstate=np.random.default_rng(0),
        show_progressbar=False, return_argmin=False,
    )
    assert len(trials) < 1000
    assert trials._fmin_cancelled or len(trials) < 20
