"""Fast-tier wall-clock budget pin (VERDICT r5 item 7b).

The README's fast-tier runtime claim kept drifting (6.5 min written,
reality creeping) because nothing in CI measured it.  This file sorts
LAST in collection (``zz``), so with the tier-1 invocation's ordering
flags (``-p no:randomly -p no:xdist``) its test runs after the whole
fast tier and sees the session's elapsed wall-clock
(``conftest.pytest_configure`` stamps the start).  Suite creep now
fails CI instead of silently invalidating the docs.

The pin only arms when the run actually deselected the slow tier
(``-m "not slow"``); full-suite runs (~40 min by design) and file
subsets are exempt.  ``FAST_TIER_BUDGET_S`` overrides the budget for
slower hardware.
"""

import os
import time

import pytest

# ~14 min single-core (the tier-1 verify command allows 870 s total,
# so the budget stays just inside the kill deadline); the measured
# round-23 fast tier is ~13.3 min on the reference container (the
# round-13..18 serve/guard/mesh/fleet suites, the round-20 graftclient
# parity/chaos suite, and the round-23 graftstorm socket-level chaos
# suite each grew it), so the default leaves ~5% headroom for machine
# variance without letting a minutes-scale regression through
DEFAULT_BUDGET_S = 840.0


def test_fast_tier_wall_clock_budget(request):
    markexpr = request.config.getoption("markexpr", default="") or ""
    if "not slow" not in markexpr.replace("'", "").replace('"', ""):
        pytest.skip("budget pin arms only on fast-tier runs (-m 'not slow')")
    budget = float(os.environ.get("FAST_TIER_BUDGET_S", DEFAULT_BUDGET_S))
    elapsed = time.monotonic() - request.config._session_t0
    assert elapsed < budget, (
        f"fast tier took {elapsed:.0f}s > budget {budget:.0f}s: a test (or "
        "several) got slower -- profile with --durations=20, move "
        "long-running additions under @pytest.mark.slow, or, if the new "
        "cost is justified, raise FAST_TIER_BUDGET_S and refresh the "
        "README's fast-tier claim in the same change"
    )
