"""Retrace-count guard for the state-threaded sequential suggest path.

A 1k-observation sequential run must compile each suggest program
exactly once per device-bucket of the log schedule -- with
MIN_CAPACITY=128 and the default compaction cap (512, then 4x steps)
the buckets visited by 1000 observations are {128, 256, 512, 2048}:
FOUR traces per program family, total.  State threading (resident
deltas, fused tell+ask, donated buffers) must not reintroduce per-pow2
double-tracing or -- the disaster case this pins against -- a retrace
per ask.  Compile counts come from the jitted functions' own trace
caches (``_cache_size``), and the transfer/dispatch schedule from the
ObsBuffer's deterministic counters, so the guard is exact, not timed.
"""

import numpy as np

from hyperopt_tpu import Trials, hp
from hyperopt_tpu import tpe_jax
from hyperopt_tpu.base import Domain, JOB_STATE_DONE
from hyperopt_tpu.fmin import partial
from hyperopt_tpu.jax_trials import JaxTrials, MIN_CAPACITY

N_OBS = 1000
N_STARTUP = 20
# log schedule for 1000 obs: 128 -> 256 -> 512 -> (cap: 4x) 2048
EXPECTED_BUCKETS = 4

SPACE = {"x": hp.uniform("x", -5, 5), "r": hp.randint("r", 4)}


def _cache_size(fn):
    # PjitFunction's own trace-cache census; the jax test suite uses it
    return fn._cache_size()


def test_sequential_1k_compiles_on_log_schedule():
    domain = Domain(lambda cfg: 0.0, SPACE)
    trials = JaxTrials(resident=True)
    algo = partial(
        tpe_jax.suggest, fused=True, n_EI_candidates=8,
        n_EI_candidates_cat=4,
    )
    rng = np.random.default_rng(0)
    for i in range(N_OBS):
        (doc,) = algo(trials.new_trial_ids(1), domain, trials, seed=i)
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float(rng.uniform(0, 9))}
        trials.insert_trial_docs([doc])
        trials.refresh()

    buf = next(iter(trials._buffers.values()))
    # the final result is ingested on the next sync (counters below are
    # about the 1000 ASK dispatches, which saw counts 0..999)
    assert buf.count == N_OBS - 1

    # one dispatch per ask, exactly (no fmin driver here, so no
    # trailing ask-ahead pre-dispatch)
    assert buf.dispatch_count == N_OBS
    # full uploads only at mirror birth + the three bucket crossings
    assert buf.full_uploads == EXPECTED_BUCKETS
    # every other warm ask fused its tell into the ask dispatch
    assert buf.delta_tells == (N_OBS - N_STARTUP) - EXPECTED_BUCKETS

    cache = domain._tpe_jax_cache
    plain = [v for k, v in cache.items() if k[-1] is False]
    fused = [v for k, v in cache.items() if k[-1] is True]
    assert len(plain) == 1 and len(fused) == 1
    # the retrace pins: one trace per bucket per program family --
    # a per-pow2 regression doubles these, a per-ask regression puts
    # them near N_OBS
    assert _cache_size(plain[0]) == EXPECTED_BUCKETS
    assert _cache_size(fused[0]) == EXPECTED_BUCKETS
    # startup prior draws share one trace (B=1, one shape)
    ps = domain._packed_space
    assert _cache_size(ps.sample_prior) == 1


def test_chunked_scan_compiles_once_across_runs_and_resume(tmp_path):
    """The round-14 chunked-scan program family: ONE trace per compiled
    chunk program (plain + callback twin) no matter how many chunks,
    runs, or resumes dispatch it -- chunk_idx/c0 are traced scalars, so
    neither the host chunk loop nor a mid-experiment resume may
    retrace.  A per-chunk regression puts these at n_chunks; a
    per-run regression at the run count."""
    import jax.numpy as jnp

    from hyperopt_tpu import hp
    from hyperopt_tpu.device_loop import compile_fmin

    space = {"x": hp.uniform("x", -5.0, 5.0)}
    rows = []
    runner = compile_fmin(
        lambda cfg: (cfg["x"] - 1.0) ** 2, space,
        max_evals=16, batch_size=2, n_startup_jobs=2, n_EI_candidates=4,
        chunk_size=4, progress_callback=rows.append, progress_every=2,
        checkpoint_path=str(tmp_path / "chunk.ckpt"), checkpoint_every=1,
    )
    assert runner._chunk_geometry["n_chunks"] == 4
    runner(seed=0)
    runner(seed=1)
    # resume of the completed seed-1 run replays from the bundle
    runner(seed=1, resume=True)
    assert _cache_size(runner._compiled_chunk) == 1
    assert _cache_size(runner._compiled_chunk_cb) == 1
