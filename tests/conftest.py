"""Test configuration: force JAX onto CPU with 8 virtual devices.

SURVEY.md SS4: multi-device behavior is tested the way the reference tests
multi-node -- by running the real thing small.  An 8-device host-platform
mesh stands in for a TPU pod slice; sharding/collective tests in
``test_sharding.py`` require it.  Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
