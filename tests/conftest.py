"""Test configuration: force JAX onto CPU with 8 virtual devices.

SURVEY.md SS4: multi-device behavior is tested the way the reference tests
multi-node -- by running the real thing small.  An 8-device host-platform
mesh stands in for a TPU pod slice; sharding/collective tests in
``test_sharding.py`` require it.  Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the ambient env may say 'axon'
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# If a TPU-tunnel PJRT plugin (e.g. 'axon') was registered by a
# sitecustomize at interpreter start, jax is already imported and its
# config may have latched JAX_PLATFORMS=axon -- override the live config
# and drop the plugin factory so tests run hermetically on the virtual
# CPU mesh even when the tunnel is wedged.  Safe no-op otherwise.
try:  # pragma: no cover - environment dependent
    import sys

    if "jax" in sys.modules:
        import jax

        jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb

    # drop only the tunnel plugin; removing real platform names (tpu, ...)
    # would break import-time lowering registrations in flax/pallas
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass


import pytest


@pytest.fixture
def cpu_mesh():
    """The graftmesh fast-tier harness: a 1-D mesh over the first n of
    this session's forced virtual CPU devices (8, see module
    docstring), so mesh parity tests run in tier-1 without real
    multi-chip hardware.  For parity checks that need a DIFFERENT
    device count than the session's, use
    :func:`hyperopt_tpu.parallel.mesh.subprocess_env_with_devices`
    (the subprocess half of the harness)."""

    def make(n, axis="study"):
        import numpy as np
        import jax
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < n:
            pytest.skip(f"needs {n} virtual devices, have {len(devs)}")
        return Mesh(np.asarray(devs[:n]), (axis,))

    return make


def pytest_configure(config):
    # session start for the fast-tier wall-clock budget pin
    # (tests/test_zz_wallclock_budget.py, VERDICT r5 item 7b): stored on
    # the config so the pin measures the WHOLE session, not its own file
    import time

    config._session_t0 = time.monotonic()
