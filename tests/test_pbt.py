"""On-device Population-Based Training (hyperopt_tpu.pbt)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperopt_tpu.pbt import compile_pbt


def quadratic_train_fn(target=0.7):
    """Analytic 'training': theta follows SGD on (theta-target)^2; loss
    is exactly known, so PBT mechanics are checkable without a net.
    A too-big lr diverges, a tiny lr crawls -- PBT must steer lr."""

    def train_fn(state, hypers, key):
        theta = state["theta"]  # [P]
        grad = 2.0 * (theta - target)
        theta = theta - hypers["lr"] * grad
        return {"theta": theta}, (theta - target) ** 2

    return train_fn


def test_pbt_steers_lr_and_converges():
    P = 8
    runner = compile_pbt(
        quadratic_train_fn(),
        {"theta": jnp.full((P,), 5.0)},
        {"lr": (1e-4, 5.0)},  # includes divergent lrs (> 1 diverges)
        pop_size=P,
        exploit_every=4,
        n_rounds=25,
    )
    out = runner(seed=0)
    assert out["n_steps"] == 100
    assert out["loss_history"].shape == (25, P)
    # converged: the best member reaches the optimum
    assert out["best_loss"] < 1e-6
    # steered: surviving lrs sit in the stable band (bad draws replaced)
    lr = out["hypers"]["lr"]
    assert (lr <= 5.0 + 1e-6).all() and (lr >= 1e-4 - 1e-9).all()
    assert np.median(out["loss_history"][-1]) < np.median(
        out["loss_history"][0]
    )


def test_pbt_reproducible_and_reusable():
    P = 4
    runner = compile_pbt(
        quadratic_train_fn(),
        {"theta": jnp.full((P,), 3.0)},
        {"lr": (1e-3, 1.0)},
        pop_size=P,
        exploit_every=3,
        n_rounds=5,
    )
    a = runner(seed=1)
    b = runner(seed=1)
    c = runner(seed=2)
    np.testing.assert_array_equal(a["loss_history"], b["loss_history"])
    assert not np.array_equal(a["loss_history"], c["loss_history"])


def test_pbt_exploit_copies_params_from_top():
    """After one round, the bottom member must carry an exact COPY of
    the top member's trained parameters (the exploit mechanic itself).

    Linear dynamics make the check exact: theta' = theta - lr each step,
    loss = theta' (lower better), so after the window every member's
    theta is -exploit_every * lr_i (all distinct w.p. 1, best = largest
    lr).  The exploit event must then leave exactly one duplicated
    theta: the bottom member holding the top member's value, which is
    the minimum."""

    def linear_train_fn(state, hypers, key):
        theta = state["theta"] - hypers["lr"]
        return {"theta": theta}, theta

    P = 4
    runner = compile_pbt(
        linear_train_fn,
        {"theta": jnp.zeros((P,))},
        {"lr": (1e-2, 1.0)},
        pop_size=P,
        exploit_every=2,
        n_rounds=1,
        exploit_quantile=0.25,
    )
    out = runner(seed=3)
    theta = np.asarray(out["state"]["theta"])
    uniq, counts = np.unique(theta, return_counts=True)
    assert len(uniq) == P - 1  # exactly one copied pair
    assert uniq[np.argmax(counts)] == theta.min()  # copied FROM the top


def test_pbt_validates_quantile_and_bounds():
    with pytest.raises(ValueError, match="must not overlap"):
        compile_pbt(
            quadratic_train_fn(), {"theta": jnp.zeros((4,))},
            {"lr": (1e-3, 1.0)}, pop_size=4, exploit_quantile=0.75,
        )
    with pytest.raises(ValueError, match="0 < low < high"):
        compile_pbt(
            quadratic_train_fn(), {"theta": jnp.zeros((4,))},
            {"lr": (0.0, 1.0)}, pop_size=4,
        )


@pytest.mark.slow
def test_pbt_sha_config_fuzz():
    """Randomized scheduler configs: every valid (pop, quantile, rounds,
    bounds) combination must produce finite, shape-correct, in-bounds
    results -- no silent NaN/shape corruption at odd sizes."""
    from hyperopt_tpu.hyperband import compile_sha

    rng = np.random.default_rng(0)
    for trial in range(8):
        P = int(rng.choice([2, 3, 4, 6, 8]))
        lo = float(10 ** rng.uniform(-4, -1))
        hi = lo * float(10 ** rng.uniform(0.5, 2))
        q = float(rng.uniform(0.1, 0.49))
        runner = compile_pbt(
            quadratic_train_fn(),
            {"theta": jnp.full((P,), float(rng.uniform(-5, 5)))},
            {"lr": (lo, hi)},
            pop_size=P,
            exploit_every=int(rng.integers(1, 5)),
            n_rounds=int(rng.integers(1, 6)),
            exploit_quantile=q,
        )
        out = runner(seed=trial)
        assert out["loss_history"].shape[1] == P
        lr = out["hypers"]["lr"]
        # relative tolerance: hypers clip in float32 LOG space, so the
        # exp roundtrip misses the bound by up to ~1e-6 relative
        assert (lr >= lo * (1 - 1e-5)).all() and (lr <= hi * (1 + 1e-5)).all()
        assert np.isfinite(list(out["best_hypers"].values())).all()

    for trial in range(6):
        eta = int(rng.choice([2, 3]))
        k = int(rng.integers(1, 3 if eta == 3 else 4))
        P = eta**k
        runner = compile_sha(
            quadratic_train_fn(),
            {"theta": jnp.full((P,), 3.0)},
            {"lr": (1e-3, 1.0)},
            n_configs=P,
            eta=eta,
            steps_per_rung=int(rng.integers(1, 4)),
        )
        out = runner(seed=trial)
        ns = [r["n"] for r in out["rungs"]]
        assert ns[0] == P and ns[-1] == 1
        assert all(a // eta == b for a, b in zip(ns, ns[1:]))
        assert np.isfinite(out["best_loss"])


@pytest.mark.slow
def test_pbt_transformer_population():
    """PBT over real model training: a TinyLM population's next-token
    loss improves and the schedule stays finite end-to-end."""
    from hyperopt_tpu.models import transformer

    P = 4
    model = transformer.TinyLM(vocab=16, d_model=16, n_heads=2,
                               n_layers=1, max_len=16)
    params = transformer.init_population(
        model, P, jax.random.key(0), seq_len=16
    )
    momentum = jax.tree.map(jnp.zeros_like, params)
    train_fn = transformer.make_pbt_train_fn(
        model, batch_size=8, seq_len=16, vocab=16
    )
    runner = compile_pbt(
        train_fn, (params, momentum), {"lr": (1e-3, 1.0), "wd": (1e-7, 1e-2)},
        pop_size=P, exploit_every=3, n_rounds=6,
    )
    out = runner(seed=0)
    assert np.isfinite(out["loss_history"]).all()
    # the POPULATION improves: compare medians, not mins -- the round-0
    # min is one lucky init draw (seed 0: 2.841 in a 2.84-3.34 spread)
    # that 6 rounds of tiny-batch training need not beat, while the
    # population median deterministically collapses 3.17 -> 2.87
    # (FAILURES.md "known test debt")
    assert (np.median(out["loss_history"][-1])
            < np.median(out["loss_history"][0]))
    assert set(out["best_hypers"]) == {"lr", "wd"}


def test_pbt_mesh_sharded_population():
    """The population axis shards over the 'trial' mesh axis (GSPMD),
    exploit's cross-member gather included."""
    from hyperopt_tpu.parallel.mesh import mesh_from_spec

    mesh = mesh_from_spec((8,), ("trial",))
    P = 8
    runner = compile_pbt(
        quadratic_train_fn(),
        {"theta": jnp.full((P,), 5.0)},
        {"lr": (1e-4, 2.0)},
        pop_size=P,
        exploit_every=3,
        n_rounds=8,
        mesh=mesh,
    )
    out = runner(seed=0)
    assert np.isfinite(out["loss_history"]).all()
    assert out["best_loss"] < 0.1


def test_pbt_resume_continues_population():
    """runner(init=prev_out) continues state + hypers for another
    n_rounds; deterministic, and training genuinely progresses."""
    P = 8
    runner = compile_pbt(
        quadratic_train_fn(),
        {"theta": jnp.full((P,), 5.0)},
        {"lr": (1e-4, 1.0)},
        pop_size=P, exploit_every=3, n_rounds=4,
    )
    first = runner(seed=0)
    resumed = runner(seed=1, init=first)
    again = runner(seed=1, init=first)
    np.testing.assert_array_equal(resumed["loss_history"],
                                  again["loss_history"])
    # the continued population picks up from the trained state: its
    # FIRST round is already at or below the original run's last
    assert np.median(resumed["loss_history"][0]) <= np.median(
        first["loss_history"][-1]
    ) * 1.5
    assert resumed["best_loss"] <= first["best_loss"]

    # bad init shapes / missing names are rejected with clear errors
    with pytest.raises(ValueError, match="must cover"):
        runner(seed=0, init={
            "state": first["state"],
            "hypers": {"lr": np.ones(3)},
        })
    with pytest.raises(ValueError, match="missing"):
        runner(seed=0, init={
            "state": first["state"],
            "hypers": {"momentum": np.ones(P)},
        })


def test_pbt_resume_roundtrips_through_checkpoint(tmp_path):
    """save_pytree/load_pytree persistence: resuming from the RELOADED
    state/hypers is bit-identical to resuming from the live ones."""
    from hyperopt_tpu.utils.checkpoint import load_pytree, save_pytree

    P = 4
    runner = compile_pbt(
        quadratic_train_fn(),
        {"theta": jnp.full((P,), 3.0)},
        {"lr": (1e-3, 1.0)},
        pop_size=P, exploit_every=2, n_rounds=3,
    )
    out = runner(seed=7)
    ckpt = {"state": out["state"], "hypers": out["hypers"]}
    path = tmp_path / "pbt.npz"
    save_pytree(ckpt, str(path))
    target = jax.tree.map(lambda x: np.zeros_like(np.asarray(x)), ckpt)
    loaded = load_pytree(target, str(path))

    a = runner(seed=9, init=out)
    b = runner(seed=9, init=loaded)
    np.testing.assert_array_equal(a["loss_history"], b["loss_history"])
    assert a["best_hypers"] == b["best_hypers"]

    # corrupted target shape is caught, not silently broadcast
    bad = jax.tree.map(lambda x: np.zeros((1,), np.float32), ckpt)
    with pytest.raises(ValueError, match="does not match target"):
        load_pytree(bad, str(path))
