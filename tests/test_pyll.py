"""Unit tests for the pyll expression layer (reference: tests/test_pyll.py,
SURVEY.md SS4: rec_eval correctness, as_apply lifting, clone/toposort,
switch laziness)."""

import numpy as np
import pytest

from hyperopt_tpu.pyll import (
    Apply,
    Literal,
    as_apply,
    clone,
    clone_merge,
    dfs,
    rec_eval,
    sample,
    scope,
    toposort,
)
from hyperopt_tpu.exceptions import PyllImportError


def test_literal_eval():
    assert rec_eval(as_apply(5)) == 5
    assert rec_eval(as_apply("abc")) == "abc"
    assert rec_eval(as_apply(None)) is None


def test_as_apply_list_tuple_dict():
    assert rec_eval(as_apply([1, 2, 3])) == [1, 2, 3]
    assert rec_eval(as_apply((1, (2, 3)))) == [1, [2, 3]]
    assert rec_eval(as_apply({"b": 2, "a": 1})) == {"a": 1, "b": 2}
    nested = as_apply({"x": [1, {"y": 2}]})
    assert rec_eval(nested) == {"x": [1, {"y": 2}]}


def test_arithmetic_operators():
    x = as_apply(3)
    y = as_apply(4)
    assert rec_eval(x + y) == 7
    assert rec_eval(x * y) == 12
    assert rec_eval(x - y) == -1
    assert rec_eval(y / x) == pytest.approx(4 / 3)
    assert rec_eval(-x) == -3
    assert rec_eval(x**2) == 9
    assert rec_eval(2 + x) == 5


def test_getitem():
    lst = as_apply([10, 20, 30])
    assert rec_eval(lst[1]) == 20
    with pytest.raises(IndexError):
        lst[5]


def test_scope_define_and_eval():
    @scope.define
    def _test_add3(a, b, c=0):
        return a + b + c

    node = scope._test_add3(1, 2, c=3)
    assert rec_eval(node) == 6
    scope.undefine("_test_add3")


def test_scope_unknown_symbol():
    with pytest.raises(AttributeError):
        scope.no_such_symbol_xyz


def test_undefined_impl_raises():
    node = Apply("never_defined_xyz", [as_apply(1)], {})
    with pytest.raises(PyllImportError):
        rec_eval(node)


def test_duplicate_define_raises():
    @scope.define
    def _dup_sym():
        return 1

    with pytest.raises(ValueError):
        scope.define_impl("_dup_sym", lambda: 2)
    scope.undefine("_dup_sym")


def test_switch_lazy():
    calls = []

    @scope.define
    def _effectful(tag):
        calls.append(tag)
        return tag

    expr = scope.switch(as_apply(1), scope._effectful("a"), scope._effectful("b"))
    assert rec_eval(expr) == "b"
    assert calls == ["b"], "switch must not evaluate unselected branches"
    scope.undefine("_effectful")


def test_switch_out_of_range():
    expr = scope.switch(as_apply(5), as_apply("a"), as_apply("b"))
    with pytest.raises(IndexError):
        rec_eval(expr)


def test_memo_substitution():
    x = as_apply(1)
    expr = x + 10
    assert rec_eval(expr) == 11
    assert rec_eval(expr, memo={x: 5}) == 15


def test_dfs_toposort_order():
    a = as_apply(1)
    b = as_apply(2)
    c = a + b
    d = c * a
    order = dfs(d)
    assert order.index(a) < order.index(c) < order.index(d)
    assert toposort(d)[-1] is d


def test_clone_independent():
    a = as_apply(2)
    expr = a + 3
    expr2 = clone(expr)
    assert expr2 is not expr
    assert rec_eval(expr2) == 5


def test_clone_with_memo_substitution():
    a = as_apply(2)
    expr = a + 3
    expr2 = clone(expr, memo={a: as_apply(10)})
    assert rec_eval(expr2) == 13
    assert rec_eval(expr) == 5


def test_clone_merge():
    a1 = scope.add(as_apply(1), as_apply(2))
    a2 = scope.add(as_apply(1), as_apply(2))
    both = scope.add(a1, a2)
    merged = clone_merge(both, merge_literals=True)
    adds = [n for n in dfs(merged) if n.name == "add"]
    assert len(adds) == 2  # the two identical inner adds merged into one
    assert rec_eval(merged) == 6


def test_cycle_detection():
    a = scope.add(as_apply(1), as_apply(2))
    a.pos_args[0] = a  # create a cycle
    with pytest.raises(RuntimeError):
        rec_eval(a, max_program_len=100)


def test_stochastic_sample_uniform():
    rng = np.random.default_rng(0)
    expr = scope.uniform(0, 1)
    draws = [sample(expr, np.random.default_rng(i)) for i in range(100)]
    assert all(0 <= d <= 1 for d in draws)
    assert 0.3 < np.mean(draws) < 0.7
    # determinism: same seed -> same draw
    assert sample(expr, np.random.default_rng(42)) == sample(
        expr, np.random.default_rng(42)
    )
    del rng


def test_stochastic_sample_composite():
    expr = {"a": scope.uniform(0, 1), "b": scope.randint(5)}
    val = sample(as_apply(expr), np.random.default_rng(3))
    assert 0 <= val["a"] <= 1
    assert val["b"] in range(5)


def test_lambda():
    from hyperopt_tpu.pyll import Lambda

    x = as_apply(0)
    fn = Lambda("inc", [("x", x)], x + 1)
    assert rec_eval(fn(41)) == 42


def test_o_len():
    assert len(as_apply((1, 2, 3))) == 3
    assert len(as_apply({"a": 1})) == 1


def test_pprint_no_crash():
    expr = scope.add(as_apply(1), scope.uniform(0, 1))
    s = str(expr)
    assert "add" in s and "uniform" in s
