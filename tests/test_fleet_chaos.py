"""Fleet-level failover chaos (ISSUE 13): replica kill mid-batch under
a transient-fault storm, router crash between forward and ack,
migration crash windows, and partition/zombie fencing -- with ZERO
lost / ZERO duplicate tells and every surviving stream bitwise
identical to the same-seed no-fault run.

Same discipline as ``tests/test_serve_chaos.py``: seeded
:class:`FaultPlan`\\ s per replica (plus one for the router and one
for the fleet coordinator), deterministic single-threaded pumping, the
client retrying exactly as a real protocol client would (re-ask with
``recover=True``, re-tell with explicit vals), and every scenario run
twice same-seed to prove bitwise repeatability.
"""

import numpy as np
import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.distributed.faults import (
    FLEET_CRASH_POINTS,
    FaultPlan,
    SimulatedCrash,
)
from hyperopt_tpu.exceptions import Overloaded, OwnershipLost
from hyperopt_tpu.serve import Fleet, FleetRouter, HashRing, SuggestService
from hyperopt_tpu.serve.fleet import fleet_salt

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _lockdep_armed(monkeypatch):
    # every scheduler any replica builds rides the lockdep sanitizer;
    # an observed lock-order inversion fails at acquisition time
    from hyperopt_tpu.analysis import lockdep

    dep = lockdep.arm_scheduler_class(monkeypatch)
    yield dep
    assert dep.inversions == 0, dep.errors


SPACE = {
    "x": hp.uniform("x", -5, 5),
    "lr": hp.loguniform("lr", -5, 0),
    "c": hp.choice("c", [0, 1]),
}
ALGO_KW = dict(n_cand=16, n_cand_cat=8)
KW = dict(max_batch=8, n_startup_jobs=2, snapshot_cadence=4, **ALGO_KW)
REPLICAS = ("r0", "r1", "r2")
NAMES = tuple(f"s{i:02d}" for i in range(9))
R = 4  # tells per study the workload must end with, exactly


def loss_fn(vals):
    return (vals["x"]) ** 2 / 10 + abs(float(np.log(vals["lr"])) + 2) / 3


def victim_rid(name="s00"):
    """The deterministic kill target: whichever replica the ring
    places ``name`` on (pure function of the guard fingerprint)."""
    ring = HashRing(REPLICAS, salt=fleet_salt("tpe", SPACE))
    return ring.owner(name)


def make_fleet(root, storm_rate=0.0, arm_victim=None, seed=0, fs=None):
    plans = {
        rid: FaultPlan(seed=seed * 100 + i, rate=storm_rate)
        for i, rid in enumerate(REPLICAS)
    }
    if arm_victim is not None:
        point, at = arm_victim
        plans[victim_rid()].arm(point, at=at)
    return Fleet(
        SPACE, root, replica_ids=list(REPLICAS), plans=plans,
        fs=fs if fs is not None else FaultPlan(seed=seed).fs(), **KW,
    )


class Client:
    """The protocol client's retry discipline, op-level: a crashed
    router is restarted and the op retried idempotently (asks with
    ``recover=True`` -- exactly-once delivery; tells with explicit
    vals -- tid-dedup)."""

    def __init__(self, fleet, router_fs=None):
        self.fleet = fleet
        self.router = (
            FleetRouter(fleet) if router_fs is None
            else FleetRouter(fleet, fs=router_fs)
        )
        self.router_crashes = 0

    def _restart(self):
        self.router_crashes += 1
        self.router = FleetRouter(self.fleet)  # fresh process, no plan

    def create(self, name, seed):
        while True:
            try:
                return self.router.create_study(name, seed=seed)
            except SimulatedCrash:
                self._restart()

    def ask(self, name):
        recover = False
        while True:
            try:
                return self.router.ask(name, timeout=30, recover=recover)
            except SimulatedCrash:
                self._restart()
                recover = True

    def tell(self, name, tid, loss, vals):
        while True:
            try:
                return self.router.tell(name, tid, loss, vals=vals)
            except SimulatedCrash:
                self._restart()


def drive(client, streams, rounds, names=NAMES):
    for _ in range(rounds):
        for n in names:
            tid, vals = client.ask(n)
            client.tell(n, tid, loss_fn(vals), vals)
            streams[n].append((tid, tuple(sorted(vals.items()))))


def final_state(fleet, names=NAMES):
    out = {}
    for n in names:
        st = fleet.replicas[fleet.route(n)].service.scheduler.study(n)
        buf = st.buf
        out[n] = {
            "count": int(buf.count),
            "tids": buf.tids[: buf.count].tolist(),
            "losses": buf.losses[: buf.count].tolist(),
            "values": buf.values[:, : buf.count].copy(),
            "wal_total_tells": st.persist.wal.total_tells,
        }
    return out


def assert_zero_lost_zero_duplicate(state):
    for n, d in state.items():
        assert d["count"] == R, (n, d["count"])
        assert len(set(d["tids"])) == R, f"{n}: duplicate tid absorbed"
        assert d["wal_total_tells"] == R, (
            f"{n}: WAL logged {d['wal_total_tells']} tells for "
            f"{R} applied -- lost or duplicated"
        )


def assert_states_bitwise_equal(a, b, names=NAMES):
    for n in names:
        assert a[n]["tids"] == b[n]["tids"], n
        assert a[n]["losses"] == b[n]["losses"], n
        np.testing.assert_array_equal(a[n]["values"], b[n]["values"])
        assert a[n]["wal_total_tells"] == b[n]["wal_total_tells"]


@pytest.fixture(scope="module")
def clean_run(tmp_path_factory):
    """The same-seed NO-FAULT run every chaos scenario's surviving
    streams are pinned against (shared across the module: the streams
    are study-local, so one clean run serves every comparison)."""
    root = tmp_path_factory.mktemp("fleet-clean")
    fleet = make_fleet(str(root))
    client = Client(fleet)
    for i, n in enumerate(NAMES):
        client.create(n, seed=100 + i)
    streams = {n: [] for n in NAMES}
    drive(client, streams, R)
    state = final_state(fleet)
    fleet.shutdown()
    return streams, state


# ---------------------------------------------------------------------------
# THE acceptance scenario
# ---------------------------------------------------------------------------


def test_replica_kill_mid_batch_under_storm_acceptance(
    tmp_path, clean_run
):
    """Kill a replica mid-batch under a 10% transient-errno storm:
    the workload completes with zero lost / zero duplicate tells
    (asserted live AND via cold WAL replay), EVERY stream -- including
    the failed-over ones -- is bitwise the same-seed no-fault run's,
    and the whole crash-and-failover scenario replays bitwise."""
    clean_streams, clean_state = clean_run
    runs = []
    for rep in range(2):
        root = str(tmp_path / f"kill-{rep}")
        fleet = make_fleet(
            root, storm_rate=0.10,
            arm_victim=("serve_mid_batch", 2), seed=7,
        )
        victim = victim_rid()
        client = Client(fleet)
        for i, n in enumerate(NAMES):
            client.create(n, seed=100 + i)
        streams = {n: [] for n in NAMES}
        drive(client, streams, R)
        # the victim actually died and its studies failed over
        assert fleet.replicas[victim].dead
        assert victim not in fleet.ring.nodes
        assert fleet.recovery_ms is not None and fleet.recovery_ms > 0
        state = final_state(fleet)
        assert_zero_lost_zero_duplicate(state)
        fleet.shutdown()

        # cold audit: re-materialize every study from nothing but its
        # WAL+bundle pair -- the independent zero-lost/zero-dup proof
        audit = SuggestService(
            SPACE, root=root, owner="audit", background=False,
            max_batch=16, n_startup_jobs=2, **ALGO_KW,
        )
        for n in NAMES:
            h = audit.create_study(n, takeover=True)
            assert h.n_tells == R, (n, h.n_tells)
        cold = {
            n: audit.scheduler.study(n).buf.tids[:R].tolist()
            for n in NAMES
        }
        audit.shutdown()
        for n in NAMES:
            assert cold[n] == state[n]["tids"], n
        runs.append((streams, state))

    # every stream bitwise identical to the same-seed no-fault run --
    # failover re-serves in-flight asks from their WAL-logged seeds,
    # so even the killed replica's studies do not diverge
    for streams, state in runs:
        assert streams == clean_streams
        assert_states_bitwise_equal(state, clean_state)
    # and the whole scenario replays bitwise
    assert runs[0][0] == runs[1][0]
    assert_states_bitwise_equal(runs[0][1], runs[1][1])


# ---------------------------------------------------------------------------
# router crash between forward and ack
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("ack_ordinal", [24, 25])
def test_router_crash_between_forward_and_ack(
    tmp_path, clean_run, ack_ordinal
):
    """The router dies AFTER the replica executed the op but BEFORE
    acking the client (ordinal 24 lands on an ask ack, 25 on a tell
    ack, behind the 9 create acks + round-1 ask/tell acks).  The
    restarted router's retry is idempotent: recover-asks re-deliver
    the already-served suggestion, re-tells dedup by tid -- streams
    stay bitwise the no-fault run's."""
    clean_streams, clean_state = clean_run
    root = str(tmp_path / "rc")
    fleet = make_fleet(root)
    rplan = FaultPlan(seed=1).arm(
        "fleet_router_after_forward_before_ack", at=ack_ordinal
    )
    client = Client(fleet, router_fs=rplan.fs())
    for i, n in enumerate(NAMES):
        client.create(n, seed=100 + i)
    streams = {n: [] for n in NAMES}
    drive(client, streams, R)
    assert client.router_crashes == 1, "the router crash never fired"
    state = final_state(fleet)
    assert_zero_lost_zero_duplicate(state)
    assert streams == clean_streams
    assert_states_bitwise_equal(state, clean_state)
    fleet.shutdown()


# ---------------------------------------------------------------------------
# migration crash windows (the drain protocol)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("point", [
    "fleet_migrate_after_snapshot_before_handoff",
    "fleet_migrate_after_handoff_before_restore",
])
def test_migration_crash_windows(tmp_path, clean_run, point):
    """Drain migration killed between snapshot and handoff (source
    still owns: migration aborts and re-runs) and between handoff and
    restore (study unowned: the re-run adopts it on the target).
    Either way the drain completes, nothing is lost or duplicated, and
    streams stay bitwise the no-fault run's."""
    clean_streams, clean_state = clean_run
    root = str(tmp_path / "mig")
    on_fleet = point.endswith("before_restore")
    victim = victim_rid()
    fleet_plan = FaultPlan(seed=2)
    if on_fleet:
        fleet_plan.arm(point, at=1)
    fleet = make_fleet(
        root,
        arm_victim=None if on_fleet else (point, 1),
        fs=fleet_plan.fs(),
    )
    client = Client(fleet)
    for i, n in enumerate(NAMES):
        client.create(n, seed=100 + i)
    streams = {n: [] for n in NAMES}
    drive(client, streams, 2)

    fleet.begin_drain(victim, timeout=5.0)
    crashes = 0
    while victim in fleet.replicas:
        try:
            fleet.complete_drain(victim)
        except SimulatedCrash:
            crashes += 1  # the coordinator died; re-run the drain
    assert crashes == 1, f"{point} never fired"
    assert victim not in fleet.ring.nodes

    drive(client, streams, R - 2)
    state = final_state(fleet)
    assert_zero_lost_zero_duplicate(state)
    assert streams == clean_streams
    assert_states_bitwise_equal(state, clean_state)
    fleet.shutdown()


# ---------------------------------------------------------------------------
# partition / zombie fencing
# ---------------------------------------------------------------------------


def test_partitioned_zombie_never_double_serves(tmp_path, clean_run):
    """A replica partitioned away from the router keeps running as a
    zombie while its studies fail over.  Every fenced op the zombie
    attempts -- ask, async ask, tell -- must raise OwnershipLost
    (claim epoch bumped by the takeover), and the surviving streams
    must be bitwise the no-fault run's: the zombie contributed
    NOTHING."""
    clean_streams, clean_state = clean_run
    root = str(tmp_path / "zombie")
    fleet = make_fleet(root)
    client = Client(fleet)
    for i, n in enumerate(NAMES):
        client.create(n, seed=100 + i)
    streams = {n: [] for n in NAMES}
    drive(client, streams, 2)

    victim = victim_rid()
    zombie = fleet.replicas[victim]
    fleet.partition(victim)
    drive(client, streams, 1)  # router fails the partitioned rid over

    znames = [n for n in NAMES if n in zombie.service.studies()]
    assert znames, "the zombie should still hold its old handles"
    for n in znames:
        with pytest.raises(OwnershipLost):
            zombie.ask(n, timeout=5)
        with pytest.raises(OwnershipLost):
            zombie.ask_async(n)
        with pytest.raises(OwnershipLost):
            zombie.tell(n, 99, 0.5, vals={"x": 0.1, "lr": 0.5, "c": 0})

    drive(client, streams, 1)
    state = final_state(fleet)
    assert_zero_lost_zero_duplicate(state)
    assert streams == clean_streams
    assert_states_bitwise_equal(state, clean_state)
    fleet.shutdown()


def test_partition_heals_zombie_rejoins_client_invisibly(
    tmp_path, clean_run
):
    """The graftstorm heal half of the zombie story: the partition
    LIFTS.  The replica was alive the whole time; ``Fleet.heal`` puts
    it back on the ring, its first routed op per study raises
    ``OwnershipLost`` (stale pre-partition claim), and the router's
    adoption path re-claims with ``takeover=True`` -- the rejoin is
    client-invisible: zero lost, zero duplicates, streams bitwise the
    never-partitioned run's."""
    clean_streams, clean_state = clean_run
    root = str(tmp_path / "heal")
    fleet = make_fleet(root)
    client = Client(fleet)
    for i, n in enumerate(NAMES):
        client.create(n, seed=100 + i)
    streams = {n: [] for n in NAMES}
    drive(client, streams, 1)

    victim = victim_rid()
    fleet.partition(victim)
    drive(client, streams, 1)  # failover serves the zombie's studies
    assert victim not in fleet.ring.nodes
    assert not fleet.replicas[victim].dead  # partitioned-but-ALIVE

    fleet.heal(victim)
    assert victim in fleet.ring.nodes
    assert not fleet.replicas[victim].partitioned
    # the healed rejoiner owns its old keys again, with stale claims
    owned = [n for n in NAMES if fleet.route(n) == victim]
    assert owned, "the heal never routed anything back"
    with pytest.raises(OwnershipLost):
        fleet.replicas[victim].ask(owned[0], timeout=5)

    drive(client, streams, R - 2)  # adoption re-claims, client-invisibly
    state = final_state(fleet)
    assert_zero_lost_zero_duplicate(state)
    assert streams == clean_streams
    assert_states_bitwise_equal(state, clean_state)
    # and the healed replica really did end up serving its keys again
    for n in owned:
        assert fleet.route(n) == victim
    fleet.shutdown()


# ---------------------------------------------------------------------------
# rolling restart: drain-migrate with typed backpressure only
# ---------------------------------------------------------------------------


def test_rolling_restart_drain_migrate(tmp_path, clean_run):
    """The planned path: drain a replica (clients see ONLY typed
    ``Overloaded(reason="draining", retry_after=<drain deadline
    left>)``), migrate its studies via snapshot -> handoff -> restore
    -> repoint, replace it with a fresh replica (which pulls back ~its
    ring share via the same migration), and finish the workload with
    streams bitwise the no-restart run's."""
    clean_streams, clean_state = clean_run
    root = str(tmp_path / "roll")
    fleet = make_fleet(root)
    client = Client(fleet)
    for i, n in enumerate(NAMES):
        client.create(n, seed=100 + i)
    streams = {n: [] for n in NAMES}
    drive(client, streams, 2)

    victim = victim_rid()
    owned = [n for n in NAMES if fleet.route(n) == victim]
    fleet.begin_drain(victim, timeout=7.5)
    with pytest.raises(Overloaded) as ei:
        client.router.ask(owned[0], timeout=5)
    assert ei.value.reason == "draining"
    assert ei.value.retry_after is not None
    assert 0 < ei.value.retry_after <= 7.5
    migrated = fleet.complete_drain(victim)
    assert migrated == sorted(owned)
    assert victim not in fleet.replicas

    # a refused submit consumed nothing from the stream: the retry
    # (now against the new owner) continues bitwise
    drive(client, streams, 1)

    # rolling replacement: the fresh replica joins and takes back ~1/N
    # of the keys -- via planned migration, nothing else moves
    before = {n: fleet.route(n) for n in NAMES}
    fleet.add_replica("r9")
    after = {n: fleet.route(n) for n in NAMES}
    moved = [n for n in NAMES if before[n] != after[n]]
    assert all(after[n] == "r9" for n in moved)
    assert len(moved) < len(NAMES)

    drive(client, streams, 1)
    state = final_state(fleet)
    assert_zero_lost_zero_duplicate(state)
    assert streams == clean_streams
    assert_states_bitwise_equal(state, clean_state)
    fleet.shutdown()


def test_fleet_points_registered():
    """The CRASH_POINTS discipline: a new fleet crash point cannot be
    added without this suite exercising it."""
    from hyperopt_tpu.distributed.faults import ALL_CRASH_POINTS

    assert set(FLEET_CRASH_POINTS) <= set(ALL_CRASH_POINTS)
    assert set(FLEET_CRASH_POINTS) == {
        "fleet_router_after_forward_before_ack",
        "fleet_migrate_after_snapshot_before_handoff",
        "fleet_migrate_after_handoff_before_restore",
        "fleet_claim_tmp_before_rename",
    }


def test_claim_publish_crash_before_rename(tmp_path):
    """fleet_claim_tmp_before_rename: the claim doc is fsynced to its
    tmp path but the rename never lands -- the store still shows NO
    claim, so a restarted replica's re-acquire wins cleanly and the
    orphan ``.tmp.<pid>`` never shadows the real claim."""
    from hyperopt_tpu.serve.fleet import StudyClaim

    root = str(tmp_path / "claims")
    plan = FaultPlan(seed=7).arm("fleet_claim_tmp_before_rename", at=1)
    with pytest.raises(SimulatedCrash):
        StudyClaim.acquire(root, "s00", "r0", fs=plan.fs())
    # the rename never happened: no claim is visible at the real path
    assert StudyClaim.read(root, "s00") is None
    # the restarted replica (fresh process, no plan) acquires cleanly
    claim = StudyClaim.acquire(root, "s00", "r1")
    assert claim.is_live()
    assert StudyClaim.read(root, "s00")["replica"] == "r1"


# ---------------------------------------------------------------------------
# the soak: 10^4 churning studies through the fleet, with a mid-soak kill
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fleet_soak_10k_churning_studies(tmp_path):
    """10,000+ studies churn through the fleet in waves (create -> 2
    batched ask+tell rounds -> close) UNDER THE AUTOSCALER (ISSUE 16):
    the fleet starts at two replicas and the pilot -- fed only by the
    scraped metrics -- grows it when wave pressure sustains, with one
    replica killed mid-soak and the quiet tail scaled back in.
    Asserts every wave completes exactly (zero lost / zero duplicate
    tells per study), the pilot both scaled out and scaled in, and
    stamps the aggregate asks/s the bench's ``bench_pilot`` mirrors at
    small scale."""
    import time

    from hyperopt_tpu.exceptions import OwnershipLost, ReplicaDead
    from hyperopt_tpu.serve import FleetPilot, PilotConfig

    n_studies = 10_000
    wave_size = 18
    rounds = 2
    root = str(tmp_path / "soak")
    # capacity headroom: start UNDER-provisioned (two replicas) -- the
    # pilot's scale-out is what absorbs the wave pressure, and after
    # the mid-soak kill the survivors absorb the victim's share
    kw = dict(KW, max_batch=32)
    fleet = Fleet(
        SPACE, root, replica_ids=["r0", "r1"],
        plans={rid: FaultPlan(seed=i) for i, rid in enumerate(REPLICAS)},
        **kw,
    )
    router = FleetRouter(fleet)
    pilot = FleetPilot(fleet, config=PilotConfig(
        min_replicas=2, max_replicas=4, queue_high=12.0, shed_high=0,
        breach_ticks=2, clear_ticks=2, cooldown_ticks=2,
    ))
    assert pilot.scrape == fleet.metrics_rows  # no test back-channel
    kill_at_wave = 3
    victim = None
    t0 = time.perf_counter()
    lat = []
    served = told = 0
    waves = (n_studies + wave_size - 1) // wave_size

    def ask_wave_under_pressure(names):
        """Round 1 of each wave: submit the whole wave async so the
        pilot's scrape sees the real queue, tick the control loop
        mid-pressure, then gather -- any study whose replica died or
        whose queue was shed by a mid-wave migration retries through
        the ordinary failover path with ``recover=True``."""
        by_rep = {}
        for n in names:
            by_rep.setdefault(fleet.route(n), []).append(n)
        futs, failed = {}, []
        for rid, group in by_rep.items():
            rep = fleet.replicas[rid]
            if rep.dead or rep.partitioned:
                failed.extend(group)
                continue
            try:
                for n in group:
                    futs[n] = (rid, rep.ask_async(n))
            except (ReplicaDead, SimulatedCrash, OwnershipLost):
                fleet.mark_dead(rid)
                fleet.failover(rid)
                failed.extend(n for n in group if n not in futs)
        pilot.tick()  # the scrape sees the queued wave
        got = {}
        for rid in {r for r, _ in futs.values()}:
            group = [(n, f) for n, (r2, f) in futs.items() if r2 == rid]
            rep = fleet.replicas[rid]
            try:
                rep.pump_until([f for _, f in group], timeout=60)
            except (ReplicaDead, SimulatedCrash, OwnershipLost):
                fleet.mark_dead(rid)
                fleet.failover(rid)
            for n, f in group:
                try:
                    got[n] = f.result(timeout=0)
                except (ValueError, ReplicaDead, SimulatedCrash,
                        OwnershipLost):
                    # shed by a pilot-driven migration or a dead
                    # owner: the WAL-logged seed re-serves identically
                    failed.append(n)
        for n in failed:
            got[n] = router.ask(n, timeout=60, recover=True)
        return got

    for w in range(waves):
        names = [
            f"w{w:04d}x{j:02d}"
            for j in range(min(wave_size, n_studies - w * wave_size))
        ]
        for j, n in enumerate(names):
            router.create_study(n, seed=w * 100 + j)
        if w == kill_at_wave:
            victim = fleet.route(names[0])
            fleet.kill_replica(victim)  # failover on first contact
        for r in range(rounds):
            t_ask = time.perf_counter()
            if r == 0:
                got = ask_wave_under_pressure(names)
            else:
                got = router.ask_batch(names, timeout=60)
            lat.append((time.perf_counter() - t_ask) / len(names))
            for n, (tid, vals) in got.items():
                router.tell(n, tid, loss_fn(vals), vals=vals)
                told += 1
            served += len(got)
        for n in names:
            st = fleet.replicas[fleet.route(n)].service.scheduler.study(n)
            assert st.buf.count == rounds, (n, st.buf.count)
            assert st.persist.wal.total_tells == rounds
            router.close_study(n)
    # the quiet tail: no queued work -> the pilot shrinks the fleet
    for _ in range(8):
        pilot.tick()
    dt = time.perf_counter() - t0
    assert served == told == n_studies * rounds
    assert fleet.replicas[victim].dead
    assert fleet.recovery_ms is not None
    prows = {
        row["name"]: row for row in pilot.metrics_rows()
        if not row.get("labels")
    }
    n_out = prows["pilot_scale_outs_total"]["value"]
    n_in = prows["pilot_scale_ins_total"]["value"]
    assert n_out >= 1, "the soak never pressured the pilot into growing"
    assert n_in >= 1, "the quiet tail never shrank the fleet"
    assert any(rid.startswith("p") for rid in fleet.replicas), (
        "no pilot-spawned replica survived to the end of the soak"
    )
    lat_ms = sorted(1000.0 * x for x in lat)
    p99 = lat_ms[min(len(lat_ms) - 1, int(0.99 * len(lat_ms)))]
    print(
        f"\nfleet soak (autoscaled): {n_studies} studies, "
        f"{served / dt:.1f} asks/s aggregate, "
        f"{n_out} scale-outs / {n_in} scale-ins, "
        f"p99 per-ask latency {p99:.2f} ms (incl. failover), "
        f"recovery {fleet.recovery_ms:.1f} ms"
    )
    fleet.shutdown()
