"""Serve chaos: the batching loop under injected crashes (ISSUE 8).

Same discipline as ``tests/test_chaos.py`` / ``test_resume_parity.py``:
seeded :class:`FaultPlan`\\ s arm the SERVE crash points (tell durable
but not applied, batch assembled but not dispatched, dispatched but not
acked), the harness catches the simulated death, restarts the service
over the same durability root, and finishes the workload.  Asserted
invariants: ZERO lost and ZERO duplicate tells (exact per-study counts,
unique tids, WAL totals), and the whole crash-and-restart scenario is
bitwise repeatable under the same seeds.
"""

import os

import numpy as np
import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.distributed.faults import (
    SERVE_CRASH_POINTS,
    FaultPlan,
    SimulatedCrash,
)
from hyperopt_tpu.exceptions import CheckpointError
from hyperopt_tpu.serve import SuggestService

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _lockdep_armed(monkeypatch):
    # the lockdep sanitizer rides every chaos scenario: crash-restart
    # loops build many schedulers, each instrumented, and any observed
    # lock-order inversion fails the test at acquisition time
    from hyperopt_tpu.analysis import lockdep

    dep = lockdep.arm_scheduler_class(monkeypatch)
    yield dep
    assert dep.inversions == 0, dep.errors


SPACE = {
    "x": hp.uniform("x", -5, 5),
    "lr": hp.loguniform("lr", -5, 0),
    "c": hp.choice("c", [0, 1]),
}

ALGO_KW = dict(n_cand=16, n_cand_cat=8)
NAMES = ("a", "b", "c")
R = 5  # tells per study the workload must end with, exactly


def loss_fn(vals):
    return (vals["x"]) ** 2 / 10 + abs(float(np.log(vals["lr"])) + 2) / 3


def _make_service(root, fs, cadence=4):
    return SuggestService(
        SPACE, root=root, fs=fs, background=False, n_startup_jobs=2,
        snapshot_cadence=cadence, max_batch=4, **ALGO_KW,
    )


def run_scenario(root, crash_point=None, crash_at=2, rate=0.0,
                 partial_rate=0.0, seed=0, cadence=4):
    """Drive every study to exactly ``R`` tells, crashing and
    restarting as the armed plan dictates.  Returns the final
    per-study state + counters."""
    plan = FaultPlan(seed=seed, rate=rate, partial_rate=partial_rate)
    if crash_point is not None:
        plan.arm(crash_point, at=crash_at)
    n_crashes = 0
    svc = None
    for _attempt in range(10):  # bounded: each crash point is one-shot
        fs = plan.fs()
        svc = _make_service(root, fs, cadence=cadence)
        try:
            handles = {
                n: svc.create_study(n, seed=30 + i)
                for i, n in enumerate(NAMES)
            }
            while True:
                live = [
                    (n, h) for n, h in handles.items()
                    if svc.scheduler.study(n).buf.count < R
                ]
                if not live:
                    break
                futs = [(n, h, h.ask_async()) for n, h in live]
                svc.pump()
                for n, h, fut in futs:
                    tid, vals = fut.result(timeout=10)
                    h.tell(tid, loss_fn(vals))
        except SimulatedCrash:
            n_crashes += 1
            continue  # a dead service publishes nothing else; restart
        break
    out = {}
    for n in NAMES:
        st = svc.scheduler.study(n)
        buf = st.buf
        out[n] = {
            "count": buf.count,
            "tids": buf.tids[: buf.count].tolist(),
            "losses": buf.losses[: buf.count].tolist(),
            "values": buf.values[:, : buf.count].copy(),
            "wal_total_tells": st.persist.wal.total_tells,
        }
    svc.shutdown()
    return out, n_crashes


@pytest.mark.parametrize("point", SERVE_CRASH_POINTS)
def test_crash_point_zero_lost_zero_duplicate(tmp_path, point):
    """Each serve crash point: the workload completes after restart
    with exactly R tells per study -- none lost, none duplicated --
    and the same-seed replay of the whole crash-and-restart scenario
    is bitwise identical."""
    runs = []
    for rep in range(2):
        root = tmp_path / f"{point}-{rep}"
        out, n_crashes = run_scenario(str(root), crash_point=point)
        assert n_crashes == 1, f"{point} never fired"
        for n, st in out.items():
            assert st["count"] == R, (point, n, st["count"])
            assert len(set(st["tids"])) == R, "duplicate tid absorbed"
            assert st["wal_total_tells"] == R, (
                f"{point}/{n}: WAL logged {st['wal_total_tells']} "
                f"tells for {R} applied -- lost or duplicated"
            )
        runs.append(out)
    for n in NAMES:
        assert runs[0][n]["tids"] == runs[1][n]["tids"]
        assert runs[0][n]["losses"] == runs[1][n]["losses"]
        np.testing.assert_array_equal(
            runs[0][n]["values"], runs[1][n]["values"]
        )


def test_crash_mid_batch_late_arm(tmp_path):
    """The mid-batch point armed deeper into the run (after snapshots
    have compacted the WAL): replay crosses a snapshot boundary."""
    out, n_crashes = run_scenario(
        str(tmp_path / "late"), crash_point="serve_mid_batch",
        crash_at=4, cadence=3,
    )
    assert n_crashes == 1
    for n, st in out.items():
        assert st["count"] == R
        assert st["wal_total_tells"] == R


def test_transient_fault_storm_completes_exactly(tmp_path):
    """A 10% transient-errno storm over every fs primitive (burst-
    bounded): the retry scaffolding absorbs it and the workload still
    lands at exactly R tells per study, twice, same-seed-identical."""
    runs = []
    for rep in range(2):
        out, n_crashes = run_scenario(
            str(tmp_path / f"storm-{rep}"), rate=0.10, seed=7,
        )
        assert n_crashes == 0
        for st in out.values():
            assert st["count"] == R
            assert st["wal_total_tells"] == R
        runs.append(out)
    for n in NAMES:
        np.testing.assert_array_equal(
            runs[0][n]["values"], runs[1][n]["values"]
        )


def test_restore_from_wal_only(tmp_path):
    """A crash before the first snapshot cadence: restore rebuilds the
    studies purely from WAL replay."""
    out, n_crashes = run_scenario(
        str(tmp_path / "walonly"),
        crash_point="serve_after_wal_before_dispatch", crash_at=3,
        cadence=10_000,  # snapshots never fire mid-run
    )
    assert n_crashes == 1
    for st in out.values():
        assert st["count"] == R
        assert st["wal_total_tells"] == R


def test_restore_refuses_foreign_study_guard(tmp_path):
    """A durability root written by a different space/algo family must
    be REFUSED, never silently reinterpreted (PR-3/6 guard law)."""
    root = str(tmp_path / "guard")
    svc = _make_service(root, FaultPlan(seed=0).fs())
    h = svc.create_study("a", seed=1)
    h.tell(0, 1.0, vals={"x": 0.5, "lr": 0.1, "c": 0})
    svc.shutdown()

    other_space = {"x": hp.uniform("x", -1, 1)}
    svc2 = SuggestService(
        other_space, root=root, background=False, max_batch=4,
    )
    with pytest.raises(CheckpointError):
        svc2.create_study("a", seed=1)
    svc2.shutdown()


def test_retell_after_lost_ack_not_duplicated(tmp_path):
    """The client-side half of exactly-once: a tell whose ack the
    crashed service lost is re-told with explicit vals after restart
    and absorbed exactly once (WAL-replayed + idempotent-by-tid)."""
    root = str(tmp_path / "retell")
    plan = FaultPlan(seed=0).arm("serve_after_wal_before_dispatch", at=1)
    svc = _make_service(root, plan.fs())
    h = svc.create_study("a", seed=9)
    fut = h.ask_async()
    svc.pump()
    tid, vals = fut.result(timeout=10)
    with pytest.raises(SimulatedCrash):
        h.tell(tid, loss_fn(vals))  # durable, applied only on restore
    # restart; re-tell the un-acked work exactly as a real client would
    svc2 = _make_service(root, FaultPlan(seed=1).fs())
    h2 = svc2.create_study("a", seed=9)
    st = svc2.scheduler.study("a")
    assert st.buf.count == 1  # the WAL-replayed tell survived
    h2.tell(tid, loss_fn(vals), vals=vals)  # lost ack -> client retries
    assert st.buf.count == 1  # absorbed exactly once
    assert st.persist.wal.total_tells == 1
    svc2.shutdown()


def test_serve_points_registered():
    """A new serve crash point cannot be added without the chaos suite
    exercising it (the CRASH_POINTS discipline)."""
    from hyperopt_tpu.distributed.faults import ALL_CRASH_POINTS

    assert set(SERVE_CRASH_POINTS) <= set(ALL_CRASH_POINTS)
    assert set(SERVE_CRASH_POINTS) == {
        "serve_after_wal_before_dispatch",
        "serve_mid_batch",
        "serve_after_dispatch_before_ack",
        "serve_group_commit_after_flush_before_barrier",
    }
