"""Execute the Mongo and Spark backends for real.

The reference's own tests run these protocols against a real temp mongod
/ local SparkSession (SURVEY.md SS4).  Neither client library exists in
this image, so ``fake_backends`` provides in-memory doubles of exactly
the client surface the backends call -- the code under test here is the
REAL ``hyperopt_tpu.distributed.mongo`` / ``spark`` (CAS reservation,
reaping, GridFS domain shipping, dispatcher threads, job-group
cancellation), not the doubles.
"""

import threading
import time

import numpy as np
import pytest

from fake_backends import install_fake_mongo, install_fake_spark

from hyperopt_tpu import STATUS_OK, fmin, hp, rand, tpe
from hyperopt_tpu.base import (
    JOB_STATE_CANCEL,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    Domain,
)
from hyperopt_tpu.models.synthetic import DOMAINS


@pytest.fixture
def fake_mongo(monkeypatch):
    return install_fake_mongo(monkeypatch)


@pytest.fixture
def fake_spark(monkeypatch):
    return install_fake_spark(monkeypatch)


def _quad(x):
    return (x - 3.0) ** 2


def _exploding(x):
    raise RuntimeError("mongo kaboom")


# ---------------------------------------------------------------------------
# MongoJobs protocol level
# ---------------------------------------------------------------------------


def _make_doc(tid, exp_key=None):
    return {
        "tid": tid,
        "state": JOB_STATE_NEW,
        "spec": None,
        "result": {"status": "new"},
        "misc": {"tid": tid, "cmd": None, "idxs": {}, "vals": {}},
        "exp_key": exp_key,
        "owner": None,
        "version": 0,
        "book_time": None,
        "refresh_time": None,
    }


def test_reserve_cas_orders_by_tid_and_is_exclusive(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoJobs

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_cas")
    for tid in (2, 0, 1):
        jobs.publish(_make_doc(tid))
    d = jobs.reserve("w1")
    assert d["tid"] == 0 and d["state"] == JOB_STATE_RUNNING
    assert d["owner"] == "w1" and d["book_time"] is not None
    assert jobs.reserve("w2")["tid"] == 1
    assert jobs.reserve("w3")["tid"] == 2
    assert jobs.reserve("w4") is None  # drained


def test_reserve_contention_each_job_taken_once(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoJobs

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_race")
    n_jobs = 40
    for tid in range(n_jobs):
        jobs.publish(_make_doc(tid))

    taken = []
    taken_lock = threading.Lock()
    start = threading.Barrier(8)

    def worker(owner):
        start.wait()  # all workers hit the queue together
        while True:
            doc = jobs.reserve(owner)
            if doc is None:
                return
            with taken_lock:
                taken.append((doc["tid"], owner))
            time.sleep(0.001)  # simulate work so reserves interleave

    threads = [
        threading.Thread(target=worker, args=(f"w{i}",)) for i in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    tids = [t for t, _ in taken]
    assert sorted(tids) == list(range(n_jobs))  # every job exactly once
    assert len({o for _, o in taken}) > 1  # really contended


def test_reserve_respects_exp_key(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoJobs

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_key")
    jobs.publish(_make_doc(0, exp_key="A"))
    jobs.publish(_make_doc(1, exp_key="B"))
    d = jobs.reserve("w", exp_key="B")
    assert d["tid"] == 1
    assert jobs.reserve("w", exp_key="B") is None  # A's job not taken


def test_reap_returns_stale_running_jobs(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoJobs

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_reap")
    jobs.publish(_make_doc(0))
    jobs.reserve("w-dead")
    assert jobs.reap(None) == 0  # disabled -> no-op
    time.sleep(0.05)
    assert jobs.reap(0.01) == 1
    doc = jobs.coll.find_one({"tid": 0})
    assert doc["state"] == JOB_STATE_NEW and doc["owner"] is None
    # reservable again after the reap
    assert jobs.reserve("w-live")["tid"] == 0


def test_complete_done_and_error_writeback(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoJobs

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_done")
    jobs.publish(_make_doc(0))
    jobs.publish(_make_doc(1))
    d0 = jobs.reserve("w")
    jobs.complete(d0, result={"status": STATUS_OK, "loss": 0.5})
    d1 = jobs.reserve("w")
    jobs.complete(d1, error=("<class 'RuntimeError'>", "kaboom"))
    done = jobs.coll.find_one({"tid": 0})
    assert done["state"] == JOB_STATE_DONE
    assert done["result"]["loss"] == 0.5
    err = jobs.coll.find_one({"tid": 1})
    assert err["state"] == JOB_STATE_ERROR
    assert err["misc"]["error"][1] == "kaboom"


def test_gridfs_attachments_roundtrip_and_replace(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoTrials

    trials = MongoTrials("mongo://localhost:27017/db_att/jobs")
    assert "blob" not in trials.attachments
    trials.attachments["blob"] = b"\x00\x01"
    assert trials.attachments["blob"] == b"\x00\x01"
    trials.attachments["blob"] = "text-replaces"  # str path + overwrite
    assert trials.attachments["blob"] == b"text-replaces"
    with pytest.raises(KeyError):
        trials.attachments["missing"]


# ---------------------------------------------------------------------------
# MongoTrials + MongoWorker end-to-end fmin
# ---------------------------------------------------------------------------


def _worker_pool(conn, n_workers, stop, exp_key=None):
    from hyperopt_tpu.distributed.mongo import MongoJobs, MongoWorker

    threads = []
    for i in range(n_workers):
        jobs = MongoJobs.new_from_connection_str(conn)
        worker = MongoWorker(jobs, exp_key=exp_key)

        def loop(w=worker, owner=f"host{i}:{1000 + i}"):
            while not stop.is_set():
                if not w.run_one(owner):
                    time.sleep(0.01)

        th = threading.Thread(target=loop, daemon=True)
        th.start()
        threads.append(th)
    return threads


def test_fmin_through_mongo_trials_with_workers(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoTrials

    conn = "localhost:27017/db_fmin"
    trials = MongoTrials(f"mongo://{conn}/jobs", exp_key="exp1")
    stop = threading.Event()
    workers = _worker_pool(conn, 2, stop)
    try:
        best = fmin(
            _quad,
            hp.uniform("x", -10, 10),
            algo=tpe.suggest,
            max_evals=10,
            trials=trials,
            rstate=np.random.default_rng(0),
            show_progressbar=False,
            max_queue_len=4,
        )
    finally:
        stop.set()
        for th in workers:
            th.join(timeout=10)
    assert len(trials) == 10
    assert all(t["state"] == JOB_STATE_DONE for t in trials.trials)
    assert "x" in best
    # results were computed by the worker threads (owner stamped by reserve)
    owners = {t["owner"] for t in trials.trials}
    assert owners <= {"host0:1000", "host1:1001"} and owners


def test_mongo_worker_marks_failed_jobs_error(fake_mongo):
    import pickle

    from hyperopt_tpu.distributed.mongo import MongoJobs, MongoWorker, MongoTrials

    conn = "localhost:27017/db_err"
    trials = MongoTrials(f"mongo://{conn}/jobs")
    domain = Domain(_exploding, hp.uniform("x", 0, 1))
    trials.attachments["FMinIter_Domain"] = pickle.dumps(domain)
    docs = rand.suggest(trials.new_trial_ids(1), domain, trials, seed=0)
    trials.insert_trial_docs(docs)

    jobs = MongoJobs.new_from_connection_str(conn)
    assert MongoWorker(jobs).run_one("w:1")
    trials.refresh()
    t = trials.trials[0]
    assert t["state"] == JOB_STATE_ERROR
    assert "mongo kaboom" in t["misc"]["error"][1]


def test_mongo_refresh_reaps_with_reserve_timeout(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoJobs, MongoTrials

    conn = "localhost:27017/db_refresh_reap"
    trials = MongoTrials(f"mongo://{conn}/jobs", reserve_timeout=0.01)
    jobs = MongoJobs.new_from_connection_str(conn)
    jobs.publish(_make_doc(0))
    jobs.reserve("w-dead")
    time.sleep(0.05)
    trials.refresh()  # reaps as a side effect
    assert jobs.coll.find_one({"tid": 0})["state"] == JOB_STATE_NEW


def test_mongo_new_trial_ids_unique_across_drivers(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoTrials

    conn = "mongo://localhost:27017/db_ids/jobs"
    t1 = MongoTrials(conn)
    t2 = MongoTrials(conn)
    ids1 = t1.new_trial_ids(3)
    domain = Domain(_quad, hp.uniform("x", -10, 10))
    docs = rand.suggest(ids1, domain, t1, seed=0)
    t1.insert_trial_docs(docs)
    ids2 = t2.new_trial_ids(3)  # second driver sees the collection floor
    assert not (set(ids1) & set(ids2))


def test_mongo_delete_all_scoped_to_exp_key(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoJobs, MongoTrials

    conn = "localhost:27017/db_del"
    jobs = MongoJobs.new_from_connection_str(conn)
    jobs.publish(_make_doc(0, exp_key="keep"))
    jobs.publish(_make_doc(1, exp_key="drop"))
    trials = MongoTrials(f"mongo://{conn}/jobs", exp_key="drop")
    trials.delete_all()
    remaining = jobs.coll.find({})
    assert [d["exp_key"] for d in remaining] == ["keep"]


def test_main_worker_cli_runs_max_jobs(fake_mongo):
    import pickle

    from hyperopt_tpu.distributed.mongo import MongoTrials, main_worker

    conn = "localhost:27017/db_cli"
    trials = MongoTrials(f"mongo://{conn}/jobs")
    domain = Domain(_quad, hp.uniform("x", -10, 10))
    trials.attachments["FMinIter_Domain"] = pickle.dumps(domain)
    docs = rand.suggest(trials.new_trial_ids(2), domain, trials, seed=0)
    trials.insert_trial_docs(docs)

    rc = main_worker(["--mongo", conn, "--max-jobs", "2"])
    assert rc == 0
    trials.refresh()
    assert [t["state"] for t in trials.trials] == [JOB_STATE_DONE] * 2
    assert all(t["result"]["status"] == STATUS_OK for t in trials.trials)


# ---------------------------------------------------------------------------
# SparkTrials
# ---------------------------------------------------------------------------


def test_spark_trials_fmin_end_to_end(fake_spark):
    from fake_backends import FakeSparkSession

    from hyperopt_tpu.distributed.spark import SparkTrials

    session = FakeSparkSession()
    trials = SparkTrials(parallelism=2, spark_session=session)
    best = fmin(
        _quad,
        hp.uniform("x", -10, 10),
        algo=rand.suggest,
        max_evals=8,
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    assert len(trials) == 8
    assert all(t["state"] == JOB_STATE_DONE for t in trials.trials)
    assert "x" in best
    # each trial really ran as its own 1-task job on the fake cluster
    assert session.sparkContext.parallelize_calls == 8
    assert all(t["owner"] == "spark" for t in trials.trials)


def test_spark_trials_battery_quality(fake_spark):
    """The reference pattern: algos are tested by running fmin end-to-end
    on the battery -- here through the Spark dispatch path."""
    from fake_backends import FakeSparkSession

    from hyperopt_tpu.distributed.spark import SparkTrials

    dom = DOMAINS["quadratic1"]
    trials = SparkTrials(parallelism=4, spark_session=FakeSparkSession())
    fmin(
        dom.fn, dom.make_space(), algo=tpe.suggest, max_evals=50,
        trials=trials, rstate=np.random.default_rng(1),
        show_progressbar=False, return_argmin=False,
    )
    assert min(trials.losses()) < 1.0


def test_spark_trials_timeout_cancels(fake_spark):
    from fake_backends import FakeSparkSession

    from hyperopt_tpu.distributed.spark import SparkTrials

    def slow(x):
        time.sleep(0.15)
        return x

    session = FakeSparkSession()
    trials = SparkTrials(parallelism=1, timeout=0.5, spark_session=session)
    fmin(
        slow, hp.uniform("x", 0, 1), algo=rand.suggest, max_evals=500,
        trials=trials, rstate=np.random.default_rng(0),
        show_progressbar=False, return_argmin=False,
    )
    assert trials._fmin_cancelled
    assert trials._fmin_cancelled_reason == "fmin run timeout"
    assert len(trials) < 500
    # the inflight job group was cancelled on the fake SparkContext
    assert session.sparkContext.cancel_calls
    states = [t["state"] for t in trials.trials]
    assert JOB_STATE_CANCEL in states or len(states) < 500


def test_spark_trials_error_capture(fake_spark):
    from fake_backends import FakeSparkSession

    from hyperopt_tpu.distributed.spark import SparkTrials

    def flaky(x):
        if x > 0:
            raise ValueError("positive!")
        return x

    trials = SparkTrials(parallelism=2, spark_session=FakeSparkSession())
    fmin(
        flaky, hp.uniform("x", -1, 1), algo=rand.suggest, max_evals=10,
        trials=trials, rstate=np.random.default_rng(3),
        show_progressbar=False, return_argmin=False,
    )
    states = {t["state"] for t in trials.trials}
    assert JOB_STATE_DONE in states and JOB_STATE_ERROR in states
    err = next(t for t in trials.trials if t["state"] == JOB_STATE_ERROR)
    assert "positive!" in err["misc"]["error"][1]


def test_spark_trials_default_session_from_builder(fake_spark):
    from hyperopt_tpu.distributed.spark import SparkTrials

    trials = SparkTrials()  # pyspark.sql.SparkSession.builder.getOrCreate()
    assert trials.parallelism == 2  # fake defaultParallelism
    assert trials._supports_cancel
