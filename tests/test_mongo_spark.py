"""Execute the Mongo and Spark backends for real.

The reference's own tests run these protocols against a real temp mongod
/ local SparkSession (SURVEY.md SS4).  Neither client library exists in
this image, so ``fake_backends`` provides in-memory doubles of exactly
the client surface the backends call -- the code under test here is the
REAL ``hyperopt_tpu.distributed.mongo`` / ``spark`` (CAS reservation,
reaping, GridFS domain shipping, dispatcher threads, job-group
cancellation), not the doubles.
"""

import threading
import time

import numpy as np
import pytest

from fake_backends import install_fake_mongo, install_fake_spark

from hyperopt_tpu import STATUS_OK, fmin, hp, rand, tpe
from hyperopt_tpu.base import (
    JOB_STATE_CANCEL,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    Domain,
    Trials,
)
from hyperopt_tpu.models.synthetic import DOMAINS


@pytest.fixture
def fake_mongo(monkeypatch):
    return install_fake_mongo(monkeypatch)


@pytest.fixture
def fake_spark(monkeypatch):
    return install_fake_spark(monkeypatch)


def _quad(x):
    return (x - 3.0) ** 2


def _exploding(x):
    raise RuntimeError("mongo kaboom")


# ---------------------------------------------------------------------------
# MongoJobs protocol level
# ---------------------------------------------------------------------------


def _make_doc(tid, exp_key=None):
    return {
        "tid": tid,
        "state": JOB_STATE_NEW,
        "spec": None,
        "result": {"status": "new"},
        "misc": {"tid": tid, "cmd": None, "idxs": {}, "vals": {}},
        "exp_key": exp_key,
        "owner": None,
        "version": 0,
        "book_time": None,
        "refresh_time": None,
    }


def test_reserve_cas_orders_by_insertion_and_is_exclusive(fake_mongo):
    """Reservation order is INSERTION order (``_id``), not tid order:
    type-neutral across numeric and string tids (ADVICE r5 -- a tid
    sort would starve asha_mongo's string tids behind numerics)."""
    from hyperopt_tpu.distributed.mongo import MongoJobs

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_cas")
    for tid in (2, 0, 1):
        jobs.publish(_make_doc(tid))
    d = jobs.reserve("w1")
    assert d["tid"] == 2 and d["state"] == JOB_STATE_RUNNING
    assert d["owner"] == "w1" and d["book_time"] is not None
    assert jobs.reserve("w2")["tid"] == 0
    assert jobs.reserve("w3")["tid"] == 1
    assert jobs.reserve("w4") is None  # drained


def test_reserve_mixed_tid_types_no_starvation(fake_mongo):
    """ADVICE r5: numeric-tid (fmin) and string-tid (asha_mongo) jobs
    coexisting on one collection are served in publication order -- BSON
    orders every number before every string, so the old tid sort would
    hand out 1, 2 first and starve the string tids behind any numeric
    backlog."""
    from hyperopt_tpu.distributed.mongo import MongoJobs

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_mixed")
    for tid in ("asha-0", 1, "asha-1", 2):
        jobs.publish(_make_doc(tid))
    order = [jobs.reserve(f"w{i}")["tid"] for i in range(4)]
    assert order == ["asha-0", 1, "asha-1", 2]
    assert jobs.reserve("w") is None


def test_reserve_contention_each_job_taken_once(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoJobs

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_race")
    n_jobs = 40
    for tid in range(n_jobs):
        jobs.publish(_make_doc(tid))

    taken = []
    taken_lock = threading.Lock()
    start = threading.Barrier(8)

    def worker(owner):
        start.wait()  # all workers hit the queue together
        while True:
            doc = jobs.reserve(owner)
            if doc is None:
                return
            with taken_lock:
                taken.append((doc["tid"], owner))
            time.sleep(0.001)  # simulate work so reserves interleave

    threads = [
        threading.Thread(target=worker, args=(f"w{i}",)) for i in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    tids = [t for t, _ in taken]
    assert sorted(tids) == list(range(n_jobs))  # every job exactly once
    assert len({o for _, o in taken}) > 1  # really contended


def test_reserve_respects_exp_key(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoJobs

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_key")
    jobs.publish(_make_doc(0, exp_key="A"))
    jobs.publish(_make_doc(1, exp_key="B"))
    d = jobs.reserve("w", exp_key="B")
    assert d["tid"] == 1
    assert jobs.reserve("w", exp_key="B") is None  # A's job not taken


def test_reap_returns_stale_running_jobs(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoJobs

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_reap")
    jobs.publish(_make_doc(0))
    jobs.reserve("w-dead")
    assert jobs.reap(None) == 0  # disabled -> no-op
    time.sleep(0.05)
    assert jobs.reap(0.01) == 1
    doc = jobs.coll.find_one({"tid": 0})
    assert doc["state"] == JOB_STATE_NEW and doc["owner"] is None
    # reservable again after the reap
    assert jobs.reserve("w-live")["tid"] == 0


def test_complete_done_and_error_writeback(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoJobs

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_done")
    jobs.publish(_make_doc(0))
    jobs.publish(_make_doc(1))
    d0 = jobs.reserve("w")
    jobs.complete(d0, result={"status": STATUS_OK, "loss": 0.5})
    d1 = jobs.reserve("w")
    jobs.complete(d1, error=("<class 'RuntimeError'>", "kaboom"))
    done = jobs.coll.find_one({"tid": 0})
    assert done["state"] == JOB_STATE_DONE
    assert done["result"]["loss"] == 0.5
    err = jobs.coll.find_one({"tid": 1})
    assert err["state"] == JOB_STATE_ERROR
    assert err["misc"]["error"][1] == "kaboom"


def test_gridfs_attachments_roundtrip_and_replace(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoTrials

    trials = MongoTrials("mongo://localhost:27017/db_att/jobs")
    assert "blob" not in trials.attachments
    trials.attachments["blob"] = b"\x00\x01"
    assert trials.attachments["blob"] == b"\x00\x01"
    trials.attachments["blob"] = "text-replaces"  # str path + overwrite
    assert trials.attachments["blob"] == b"text-replaces"
    with pytest.raises(KeyError):
        trials.attachments["missing"]


# ---------------------------------------------------------------------------
# MongoTrials + MongoWorker end-to-end fmin
# ---------------------------------------------------------------------------


def _worker_pool(conn, n_workers, stop, exp_key=None):
    from hyperopt_tpu.distributed.mongo import MongoJobs, MongoWorker

    threads = []
    for i in range(n_workers):
        jobs = MongoJobs.new_from_connection_str(conn)
        worker = MongoWorker(jobs, exp_key=exp_key)

        def loop(w=worker, owner=f"host{i}:{1000 + i}"):
            while not stop.is_set():
                if not w.run_one(owner):
                    time.sleep(0.01)

        th = threading.Thread(target=loop, daemon=True)
        th.start()
        threads.append(th)
    return threads


def test_fmin_through_mongo_trials_with_workers(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoTrials

    conn = "localhost:27017/db_fmin"
    trials = MongoTrials(f"mongo://{conn}/jobs", exp_key="exp1")
    stop = threading.Event()
    workers = _worker_pool(conn, 2, stop)
    try:
        best = fmin(
            _quad,
            hp.uniform("x", -10, 10),
            algo=tpe.suggest,
            max_evals=10,
            trials=trials,
            rstate=np.random.default_rng(0),
            show_progressbar=False,
            max_queue_len=4,
        )
    finally:
        stop.set()
        for th in workers:
            th.join(timeout=10)
    assert len(trials) == 10
    assert all(t["state"] == JOB_STATE_DONE for t in trials.trials)
    assert "x" in best
    # results were computed by the worker threads (owner stamped by reserve)
    owners = {t["owner"] for t in trials.trials}
    assert owners <= {"host0:1000", "host1:1001"} and owners


def test_mongo_worker_marks_failed_jobs_error(fake_mongo):
    import pickle

    from hyperopt_tpu.distributed.mongo import MongoJobs, MongoWorker, MongoTrials

    conn = "localhost:27017/db_err"
    trials = MongoTrials(f"mongo://{conn}/jobs")
    domain = Domain(_exploding, hp.uniform("x", 0, 1))
    trials.attachments["FMinIter_Domain"] = pickle.dumps(domain)
    docs = rand.suggest(trials.new_trial_ids(1), domain, trials, seed=0)
    trials.insert_trial_docs(docs)

    jobs = MongoJobs.new_from_connection_str(conn)
    assert MongoWorker(jobs).run_one("w:1")
    trials.refresh()
    t = trials.trials[0]
    assert t["state"] == JOB_STATE_ERROR
    assert "mongo kaboom" in t["misc"]["error"][1]


def test_asha_mongo_end_to_end(fake_mongo):
    """The async scheduler over the Mongo worker backend: ASHA
    promotion decisions on the driver, budget-aware evaluations farmed
    through the jobs collection's CAS to MongoWorker threads -- the
    Mongo twin of asha_filequeue (shared _TransportDriver)."""
    from hyperopt_tpu.distributed.asha_queue import asha_mongo
    from hyperopt_tpu.distributed.mongo import MongoJobs
    from hyperopt_tpu.models.synthetic import (
        budgeted_quadratic_fn, budgeted_quadratic_space,
    )

    conn = "localhost:27017/db_asha"
    stop = threading.Event()
    workers = _worker_pool(conn, 2, stop)
    try:
        out = asha_mongo(
            budgeted_quadratic_fn, budgeted_quadratic_space(),
            max_budget=9, mongo=conn, eta=3, max_jobs=30, inflight=4,
            rstate=np.random.default_rng(0), eval_timeout=120.0,
            poll_interval=0.02,
        )
    finally:
        stop.set()
        for th in workers:
            th.join(timeout=10)
    trials = out["trials"]
    assert len(trials) == 30
    budgets = [t["result"]["budget"] for t in trials.trials]
    assert set(budgets) <= {1, 3, 9}
    assert budgets.count(1) > budgets.count(9) > 0
    x_at = lambda b: {
        round(t["misc"]["vals"]["x"][0], 9)
        for t in trials.trials if t["result"]["budget"] == b
    }
    assert x_at(3) <= x_at(1) and x_at(9) <= x_at(3)
    assert np.isfinite(out["best_loss"])
    # transport record: every job completed by a WORKER thread's owner,
    # with its rung budget on the doc
    jobs = MongoJobs.new_from_connection_str(conn)
    done = list(jobs.coll.find({"state": JOB_STATE_DONE}))
    assert len(done) == 30
    assert {d["owner"] for d in done} <= {"host0:1000", "host1:1001"}
    assert {d["misc"]["budget"] for d in done} <= {1, 3, 9}


def test_asha_spark_end_to_end(fake_spark):
    """The async scheduler over the SparkTrials execution model: each
    evaluation a 1-task Spark job under its own job group, promotion
    decisions on the driver -- the third transport sharing the asha
    seam (filequeue / Mongo / Spark)."""
    from pyspark.sql import SparkSession

    from hyperopt_tpu.distributed.asha_queue import asha_spark
    from hyperopt_tpu.models.synthetic import (
        budgeted_quadratic_fn, budgeted_quadratic_space,
    )

    spark = SparkSession.builder.getOrCreate()
    out = asha_spark(
        budgeted_quadratic_fn, budgeted_quadratic_space(),
        max_budget=9, spark=spark, eta=3, max_jobs=30, inflight=4,
        rstate=np.random.default_rng(0),
    )
    trials = out["trials"]
    assert len(trials) == 30
    budgets = [t["result"]["budget"] for t in trials.trials]
    assert set(budgets) <= {1, 3, 9}
    assert budgets.count(1) > budgets.count(9) > 0
    x_at = lambda b: {
        round(t["misc"]["vals"]["x"][0], 9)
        for t in trials.trials if t["result"]["budget"] == b
    }
    assert x_at(3) <= x_at(1) and x_at(9) <= x_at(3)
    assert np.isfinite(out["best_loss"])
    # every evaluation went THROUGH the Spark dispatch (one 1-task job
    # per evaluation)
    assert spark.sparkContext.parallelize_calls == 30


def test_asha_drivers_reject_any_queue_backed_trials(fake_mongo, tmp_path):
    """Cross-backend foot-gun: each driver must refuse EVERY
    queue-backed store (FileTrials to asha_mongo and vice versa), not
    just its own backend's -- any store whose insert publishes or
    evaluates docs corrupts the scheduler bookkeeping."""
    from hyperopt_tpu.distributed import FileTrials, ThreadTrials
    from hyperopt_tpu.distributed.asha_queue import asha_filequeue, asha_mongo
    from hyperopt_tpu.distributed.mongo import MongoTrials
    from hyperopt_tpu.models.synthetic import (
        budgeted_quadratic_fn, budgeted_quadratic_space,
    )

    file_store = FileTrials(str(tmp_path / "other"), reserve_timeout=None)
    mongo_store = MongoTrials("mongo://localhost:27017/db_guard/jobs")
    for store in (file_store, mongo_store, ThreadTrials(parallelism=2)):
        with pytest.raises(ValueError, match="in-memory Trials"):
            asha_mongo(
                budgeted_quadratic_fn, budgeted_quadratic_space(),
                max_budget=4, mongo="localhost:27017/db_guard2",
                trials=store,
            )
        with pytest.raises(ValueError, match="in-memory Trials"):
            asha_filequeue(
                budgeted_quadratic_fn, budgeted_quadratic_space(),
                max_budget=4, dirpath=str(tmp_path / "q"), trials=store,
            )


def _mongo_objective_a(x):
    return 10.0 + x


def _mongo_objective_b(x):
    return 20.0 + x


def test_mongo_worker_gives_back_job_when_domain_missing(fake_mongo):
    """A MongoWorker that cannot load the doc's named Domain returns
    the job to NEW and raises (it must not drain the queue marking
    healthy jobs ERROR)."""
    from hyperopt_tpu.distributed.mongo import MongoJobs, MongoWorker

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_giveback")
    doc = _make_doc(0)
    doc["misc"]["cmd"] = ("domain_attachment", "FMinIter_Domain.asha-dead")
    jobs.publish(doc)
    with pytest.raises(KeyError, match="asha-dead"):
        MongoWorker(jobs).run_one("w:1")
    stored = jobs.coll.find_one({"tid": 0})
    assert stored["state"] == JOB_STATE_NEW and stored["owner"] is None
    assert jobs.reserve("w:2") is not None  # reservable again


def test_mongo_worker_resolves_domain_per_doc_cmd(fake_mongo):
    """Two drivers sharing one database: each doc's cmd names its own
    GridFS Domain, so a worker evaluates every job with the right
    objective -- asha_mongo's per-run key never clobbers a concurrent
    fmin's Domain."""
    import pickle

    from hyperopt_tpu.distributed.mongo import MongoJobs, MongoWorker

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_percmd")
    space = hp.uniform("x", 0, 1)
    jobs.set_attachment(
        "FMinIter_Domain", pickle.dumps(Domain(_mongo_objective_a, space))
    )
    jobs.set_attachment(
        "FMinIter_Domain.asha-x1",
        pickle.dumps(Domain(_mongo_objective_b, space)),
    )
    for tid, key in ((0, "FMinIter_Domain"), (1, "FMinIter_Domain.asha-x1")):
        doc = _make_doc(tid)
        doc["misc"]["cmd"] = ("domain_attachment", key)
        doc["misc"]["idxs"] = {"x": [tid]}
        doc["misc"]["vals"] = {"x": [0.5]}
        jobs.publish(doc)
    worker = MongoWorker(jobs)
    assert worker.run_one("w:1") and worker.run_one("w:1")
    by_tid = {
        d["tid"]: d["result"]["loss"]
        for d in jobs.coll.find({"state": JOB_STATE_DONE})
    }
    assert 10.0 <= by_tid[0] < 11.0  # fmin's Domain, untouched
    assert 20.0 <= by_tid[1] < 21.0  # asha's per-run Domain


def test_mongo_worker_heartbeat_defeats_reaping_of_live_jobs(fake_mongo):
    """An evaluation longer than the reserve timeout keeps its claim:
    the worker heartbeat refreshes book_time, so reap() (including the
    asha_mongo driver's) recycles only genuinely dead workers' jobs."""
    import pickle

    from hyperopt_tpu.distributed.mongo import MongoJobs, MongoWorker

    jobs = MongoJobs.new_from_connection_str("localhost:27017/db_beat")
    space = hp.uniform("x", 0, 1)
    jobs.set_attachment(
        "FMinIter_Domain", pickle.dumps(Domain(_mongo_slow_objective, space))
    )
    doc = _make_doc(0)
    doc["misc"]["idxs"] = {"x": [0]}
    doc["misc"]["vals"] = {"x": [0.5]}
    jobs.publish(doc)
    worker = MongoWorker(jobs, heartbeat=0.05)
    th = threading.Thread(target=worker.run_one, args=("w:1",))
    th.start()
    time.sleep(0.35)  # well past a 0.15s reserve timeout, eval running
    assert jobs.reap(reserve_timeout=0.15) == 0  # claim stays alive
    th.join(timeout=10)
    assert jobs.coll.find_one({"tid": 0})["state"] == JOB_STATE_DONE


def _mongo_slow_objective(x):
    time.sleep(0.6)
    return x


def test_mongo_worker_reloads_republished_domain(fake_mongo):
    """A long-lived MongoWorker must pick up a RE-published Domain (a
    new driver reusing the database) -- the cache is keyed by the
    GridFS file's _id, which set_attachment rotates."""
    import pickle

    from hyperopt_tpu.distributed.mongo import MongoJobs, MongoWorker

    conn = "localhost:27017/db_redomain"
    jobs = MongoJobs.new_from_connection_str(conn)
    space = hp.uniform("x", 0, 1)
    domain_a = Domain(_mongo_objective_a, space)
    jobs.set_attachment("FMinIter_Domain", pickle.dumps(domain_a))
    trials = Trials()
    docs = rand.suggest(trials.new_trial_ids(1), domain_a, trials, seed=0)
    jobs.publish(docs[0])
    worker = MongoWorker(jobs)
    assert worker.run_one("w:1")
    domain_b = Domain(_mongo_objective_b, space)
    jobs.set_attachment("FMinIter_Domain", pickle.dumps(domain_b))
    docs = rand.suggest(trials.new_trial_ids(1), domain_b, trials, seed=1)
    jobs.publish(docs[0])
    assert worker.run_one("w:1")  # SAME worker instance, new domain
    losses = sorted(
        d["result"]["loss"]
        for d in jobs.coll.find({"state": JOB_STATE_DONE})
    )
    assert 10.0 <= losses[0] < 11.0  # first domain
    assert 20.0 <= losses[1] < 21.0  # re-published domain, same cache


def test_mongo_refresh_reaps_with_reserve_timeout(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoJobs, MongoTrials

    conn = "localhost:27017/db_refresh_reap"
    trials = MongoTrials(f"mongo://{conn}/jobs", reserve_timeout=0.01)
    jobs = MongoJobs.new_from_connection_str(conn)
    jobs.publish(_make_doc(0))
    jobs.reserve("w-dead")
    time.sleep(0.05)
    trials.refresh()  # reaps as a side effect
    assert jobs.coll.find_one({"tid": 0})["state"] == JOB_STATE_NEW


def test_mongo_new_trial_ids_unique_across_drivers(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoTrials

    conn = "mongo://localhost:27017/db_ids/jobs"
    t1 = MongoTrials(conn)
    t2 = MongoTrials(conn)
    ids1 = t1.new_trial_ids(3)
    domain = Domain(_quad, hp.uniform("x", -10, 10))
    docs = rand.suggest(ids1, domain, t1, seed=0)
    t1.insert_trial_docs(docs)
    ids2 = t2.new_trial_ids(3)  # second driver sees the collection floor
    assert not (set(ids1) & set(ids2))


def test_mongo_delete_all_scoped_to_exp_key(fake_mongo):
    from hyperopt_tpu.distributed.mongo import MongoJobs, MongoTrials

    conn = "localhost:27017/db_del"
    jobs = MongoJobs.new_from_connection_str(conn)
    jobs.publish(_make_doc(0, exp_key="keep"))
    jobs.publish(_make_doc(1, exp_key="drop"))
    trials = MongoTrials(f"mongo://{conn}/jobs", exp_key="drop")
    trials.delete_all()
    remaining = jobs.coll.find({})
    assert [d["exp_key"] for d in remaining] == ["keep"]


def test_main_worker_cli_runs_max_jobs(fake_mongo):
    import pickle

    from hyperopt_tpu.distributed.mongo import MongoTrials, main_worker

    conn = "localhost:27017/db_cli"
    trials = MongoTrials(f"mongo://{conn}/jobs")
    domain = Domain(_quad, hp.uniform("x", -10, 10))
    trials.attachments["FMinIter_Domain"] = pickle.dumps(domain)
    docs = rand.suggest(trials.new_trial_ids(2), domain, trials, seed=0)
    trials.insert_trial_docs(docs)

    rc = main_worker(["--mongo", conn, "--max-jobs", "2"])
    assert rc == 0
    trials.refresh()
    assert [t["state"] for t in trials.trials] == [JOB_STATE_DONE] * 2
    assert all(t["result"]["status"] == STATUS_OK for t in trials.trials)


# ---------------------------------------------------------------------------
# SparkTrials
# ---------------------------------------------------------------------------


def test_spark_trials_fmin_end_to_end(fake_spark):
    from fake_backends import FakeSparkSession

    from hyperopt_tpu.distributed.spark import SparkTrials

    session = FakeSparkSession()
    trials = SparkTrials(parallelism=2, spark_session=session)
    best = fmin(
        _quad,
        hp.uniform("x", -10, 10),
        algo=rand.suggest,
        max_evals=8,
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    assert len(trials) == 8
    assert all(t["state"] == JOB_STATE_DONE for t in trials.trials)
    assert "x" in best
    # each trial really ran as its own 1-task job on the fake cluster
    assert session.sparkContext.parallelize_calls == 8
    assert all(t["owner"] == "spark" for t in trials.trials)


def test_spark_trials_battery_quality(fake_spark):
    """The reference pattern: algos are tested by running fmin end-to-end
    on the battery -- here through the Spark dispatch path."""
    from fake_backends import FakeSparkSession

    from hyperopt_tpu.distributed.spark import SparkTrials

    dom = DOMAINS["quadratic1"]
    trials = SparkTrials(parallelism=4, spark_session=FakeSparkSession())
    fmin(
        dom.fn, dom.make_space(), algo=tpe.suggest, max_evals=50,
        trials=trials, rstate=np.random.default_rng(1),
        show_progressbar=False, return_argmin=False,
    )
    assert min(trials.losses()) < 1.0


def test_spark_trials_timeout_cancels(fake_spark):
    from fake_backends import FakeSparkSession

    from hyperopt_tpu.distributed.spark import SparkTrials

    def slow(x):
        time.sleep(0.15)
        return x

    session = FakeSparkSession()
    trials = SparkTrials(parallelism=1, timeout=0.5, spark_session=session)
    fmin(
        slow, hp.uniform("x", 0, 1), algo=rand.suggest, max_evals=500,
        trials=trials, rstate=np.random.default_rng(0),
        show_progressbar=False, return_argmin=False,
    )
    assert trials._fmin_cancelled
    assert trials._fmin_cancelled_reason == "fmin run timeout"
    assert len(trials) < 500
    # the inflight job group was cancelled on the fake SparkContext
    assert session.sparkContext.cancel_calls
    states = [t["state"] for t in trials.trials]
    assert JOB_STATE_CANCEL in states or len(states) < 500


def test_spark_trials_error_capture(fake_spark):
    from fake_backends import FakeSparkSession

    from hyperopt_tpu.distributed.spark import SparkTrials

    def flaky(x):
        if x > 0:
            raise ValueError("positive!")
        return x

    trials = SparkTrials(parallelism=2, spark_session=FakeSparkSession())
    fmin(
        flaky, hp.uniform("x", -1, 1), algo=rand.suggest, max_evals=10,
        trials=trials, rstate=np.random.default_rng(3),
        show_progressbar=False, return_argmin=False,
    )
    states = {t["state"] for t in trials.trials}
    assert JOB_STATE_DONE in states and JOB_STATE_ERROR in states
    err = next(t for t in trials.trials if t["state"] == JOB_STATE_ERROR)
    assert "positive!" in err["misc"]["error"][1]


def test_spark_trials_default_session_from_builder(fake_spark):
    from hyperopt_tpu.distributed.spark import SparkTrials

    trials = SparkTrials()  # pyspark.sql.SparkSession.builder.getOrCreate()
    assert trials.parallelism == 2  # fake defaultParallelism
    assert trials._supports_cancel


# ---------------------------------------------------------------------------
# Double fidelity: operator semantics + sort stability (VERDICT r3 item 6)
# ---------------------------------------------------------------------------


def test_fake_match_operator_semantics():
    """The slice of mongo query semantics the backends rely on, pinned
    against the documented server behavior (range operators never match
    missing/None; $exists tests presence, not truthiness)."""
    from fake_backends import _match

    doc = {"a": 3, "b": {"c": None}, "tid": 5}
    assert _match(doc, {"a": {"$lte": 3}})
    assert not _match(doc, {"a": {"$lt": 3}})
    assert _match(doc, {"a": {"$gte": 3}})
    assert not _match(doc, {"a": {"$gt": 3}})
    assert _match(doc, {"a": {"$ne": 4}})
    assert not _match(doc, {"a": {"$ne": 3}})
    assert _match(doc, {"a": {"$in": [1, 3]}})
    assert not _match(doc, {"a": {"$in": [2]}})
    assert _match(doc, {"b.c": {"$exists": True}})  # present, value None
    assert not _match(doc, {"b.d": {"$exists": True}})
    assert _match(doc, {"b.d": {"$exists": False}})
    # a missing or None field NEVER satisfies a range operator
    assert not _match(doc, {"b.c": {"$lt": 10}})
    assert not _match(doc, {"zz": {"$gt": 0}})
    # equality against missing behaves like None (mongo null semantics)
    assert _match(doc, {"zz": None}) and _match(doc, {"b.c": None})


def test_fake_update_set_unset_inc():
    from fake_backends import Collection, _get_path

    doc = {"a": {"b": 1}, "x": 2, "n": 5}
    Collection._apply_update(
        doc, {"$set": {"a.c": 7}, "$unset": {"x": ""}, "$inc": {"n": 2}}
    )
    assert doc["a"] == {"b": 1, "c": 7}
    assert "x" not in doc
    assert doc["n"] == 7
    # $unset of a missing path is a no-op; $inc creates from 0
    Collection._apply_update(doc, {"$unset": {"zz.q": ""}, "$inc": {"m": 3}})
    assert doc["m"] == 3
    assert _get_path(doc, "a.c") == (7, True)


def test_fake_set_get_path_roundtrip_property():
    """Random dotted paths: set-then-get round-trips; intermediate
    levels materialize as dicts; unrelated keys survive."""
    import random

    from fake_backends import _get_path, _set_path, _unset_path

    rng = random.Random(0)
    for _ in range(200):
        depth = rng.randint(1, 4)
        path = ".".join(
            rng.choice("abcde") for _ in range(depth)
        )
        doc = {"keep": 1}
        val = rng.randint(0, 10**6)
        _set_path(doc, path, val)
        assert _get_path(doc, path) == (val, True)
        assert doc["keep"] == 1
        _unset_path(doc, path)
        assert _get_path(doc, path)[1] is False


def test_fake_sort_multikey_stability_property():
    """The double's multi-key sort must match the reference semantics:
    sort by key[0] first, later keys break ties, and documents equal
    under ALL keys keep insertion order (mongod sorts are stable for
    equal keys in practice; the CAS's tid tie-break relies on it)."""
    import random

    from fake_backends import Collection, _get_path

    rng = random.Random(1)
    docs = [
        {"i": i, "a": rng.randint(0, 3), "b": rng.randint(0, 2)}
        for i in range(60)
    ]
    sort = [("a", 1), ("b", -1)]
    got = Collection._sorted(docs, sort)
    want = sorted(
        docs, key=lambda d: (_get_path(d, "a")[0], -_get_path(d, "b")[0])
    )
    assert [d["i"] for d in got] == [d["i"] for d in want]
    # stability under full ties: docs with equal (a, b) keep insertion order
    for a in range(4):
        for b in range(3):
            grp = [d["i"] for d in got if d["a"] == a and d["b"] == b]
            assert grp == sorted(grp)


# ---------------------------------------------------------------------------
# Cross-PROCESS contention through the file-backed double
# ---------------------------------------------------------------------------


def _worker_env():
    """Subprocess env for workers that bootstrap the fake backends."""
    import os

    tests_dir = os.path.dirname(os.path.abspath(__file__))
    repo_root = os.path.dirname(tests_dir)
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        tests_dir + os.pathsep + repo_root + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def _spawn_workers(args_list, timeout=120):
    import subprocess
    import sys as _sys

    env = _worker_env()
    procs = [
        subprocess.Popen(
            [_sys.executable] + argv, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for argv in args_list
    ]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=timeout)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


@pytest.mark.slow
def test_reserve_cas_exclusive_across_processes(fake_mongo, tmp_path):
    """VERDICT r3 item 6: the reserve CAS proven exclusive across real
    PROCESS boundaries, not just threads -- 4 worker processes drain one
    file-backed jobs collection through the REAL MongoJobs.reserve;
    every job is taken exactly once and the work really spreads."""
    import textwrap

    from hyperopt_tpu.distributed.mongo import MongoJobs

    conn = f"file:{tmp_path}/srv/db_xproc"
    jobs = MongoJobs.new_from_connection_str(conn)
    n_jobs = 24
    for tid in range(n_jobs):
        jobs.publish(_make_doc(tid))

    worker_src = textwrap.dedent("""
        import sys, time
        import fake_backends
        fake_backends.install_fake_mongo_modules()
        from hyperopt_tpu.distributed.mongo import MongoJobs
        jobs = MongoJobs.new_from_connection_str(sys.argv[1])
        got = []
        while True:
            d = jobs.reserve(f"proc{sys.argv[2]}")
            if d is None:
                break
            got.append(d["tid"])
            time.sleep(0.005)  # hold the job so reserves interleave
        print("TAKEN", sys.argv[2], sorted(got), flush=True)
    """)
    script = tmp_path / "xproc_worker.py"
    script.write_text(worker_src)
    procs, outs = _spawn_workers(
        [[str(script), conn, str(i)] for i in range(4)]
    )
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    taken = []
    owners_with_work = 0
    for i, out in enumerate(outs):
        line = next(l for l in out.splitlines() if l.startswith("TAKEN"))
        tids = eval(line.split(None, 2)[2])
        owners_with_work += bool(tids)
        taken.extend(tids)
    assert sorted(taken) == list(range(n_jobs))  # exactly once each
    assert owners_with_work >= 2  # really contended across processes


def test_mongo_fmin_with_worker_subprocesses(fake_mongo, tmp_path):
    """The reference's TempMongo test shape without mongod: an async
    fmin drives the file-backed queue while REAL worker subprocesses run
    the main_worker CLI loop (reserve -> unpickle Domain from GridFS ->
    evaluate -> write back) across process boundaries."""
    import textwrap

    from hyperopt_tpu.distributed.mongo import MongoTrials
    from hyperopt_tpu.models.synthetic import _quadratic1_fn

    conn = f"file:{tmp_path}/srv/db_e2e"
    trials = MongoTrials(f"mongo://{conn}/jobs")

    worker_src = textwrap.dedent("""
        import sys
        import fake_backends
        fake_backends.install_fake_mongo_modules()
        from hyperopt_tpu.distributed.mongo import main_worker
        sys.exit(main_worker([
            "--mongo", sys.argv[1], "--max-jobs", sys.argv[2],
            "--poll-interval", "0.05",
        ]))
    """)
    script = tmp_path / "e2e_worker.py"
    script.write_text(worker_src)

    import subprocess
    import sys as _sys

    env = _worker_env()
    n_evals = 8
    workers = [
        subprocess.Popen(
            [_sys.executable, str(script), conn, str(n_evals // 2)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for _ in range(2)
    ]
    try:
        best = fmin(
            _quadratic1_fn,
            hp.uniform("x", -5, 5),
            algo=rand.suggest,
            max_evals=n_evals,
            trials=trials,
            rstate=np.random.default_rng(0),
            show_progressbar=False,
        )
        outs = [w.communicate(timeout=60)[0] for w in workers]
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
    for w, out in zip(workers, outs):
        assert w.returncode == 0, out[-2000:]
    trials.refresh()
    assert len(trials) == n_evals
    assert all(t["state"] == JOB_STATE_DONE for t in trials.trials)
    assert "x" in best
    owners = {t["owner"] for t in trials.trials if t["owner"]}
    assert len(owners) >= 1  # evaluated by the worker processes
    assert all(t["result"]["status"] == STATUS_OK for t in trials.trials)


# ---------------------------------------------------------------------------
# Import-gated REAL mongod test (activates when the environment has one)
# ---------------------------------------------------------------------------


def _have_real_mongo():
    import importlib.util
    import shutil

    if shutil.which("mongod") is None:
        return False
    spec = importlib.util.find_spec("pymongo")
    # the in-memory double installs fake modules only inside fixtures;
    # here we need the REAL client package on disk
    return spec is not None and "fake" not in str(spec.origin or "")


@pytest.mark.skipif(
    not _have_real_mongo(), reason="mongod/pymongo not available"
)
def test_real_mongod_end_to_end(tmp_path):
    """The reference's own strategy (SURVEY.md SS4 TempMongo): a real
    temporary mongod + the worker CLI as subprocesses.  Skipped in this
    image (no mongod); activates unchanged wherever one exists."""
    import socket
    import subprocess
    import sys as _sys
    import time as _time

    from hyperopt_tpu.distributed.mongo import MongoTrials
    from hyperopt_tpu.models.synthetic import _quadratic1_fn

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    dbdir = tmp_path / "db"
    dbdir.mkdir()
    mongod = subprocess.Popen(
        ["mongod", "--dbpath", str(dbdir), "--port", str(port),
         "--bind_ip", "127.0.0.1"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        deadline = _time.monotonic() + 30
        while True:  # wait for the server to accept connections
            try:
                with socket.create_connection(("127.0.0.1", port), 1):
                    break
            except OSError:
                if _time.monotonic() > deadline:
                    raise RuntimeError("mongod did not start")
                _time.sleep(0.2)
        conn = f"127.0.0.1:{port}/db_real"
        trials = MongoTrials(f"mongo://{conn}/jobs")
        worker = subprocess.Popen(
            [_sys.executable, "-c",
             "import sys; from hyperopt_tpu.distributed.mongo import "
             "main_worker; sys.exit(main_worker(sys.argv[1:]))",
             "--mongo", conn, "--max-jobs", "6", "--poll-interval", "0.05"],
        )
        try:
            best = fmin(
                _quadratic1_fn, hp.uniform("x", -5, 5), algo=rand.suggest,
                max_evals=6, trials=trials,
                rstate=np.random.default_rng(0), show_progressbar=False,
            )
        finally:
            worker.wait(timeout=60)
        assert "x" in best
        trials.refresh()
        assert all(t["state"] == JOB_STATE_DONE for t in trials.trials)
    finally:
        mongod.terminate()
        mongod.wait(timeout=30)


# ---------------------------------------------------------------------------
# Import-gated REAL pyspark test (activates when the environment has it)
# ---------------------------------------------------------------------------


def _have_real_pyspark():
    import importlib.util

    spec = importlib.util.find_spec("pyspark")
    # the in-memory double installs fake modules only inside fixtures;
    # here we need the REAL package on disk
    return spec is not None and "fake" not in str(spec.origin or "")


@pytest.mark.skipif(
    not _have_real_pyspark(), reason="pyspark not available"
)
def test_real_spark_local_end_to_end():
    """The reference's own strategy (SURVEY.md SS4 Spark row): a REAL
    local-mode SparkSession ("local[*]") -- multi-task without a
    cluster.  Skipped in this image (no pyspark); activates unchanged
    wherever it exists, mirroring the real-mongod gate above."""
    import pyspark

    from hyperopt_tpu.distributed.spark import SparkTrials
    from hyperopt_tpu.models.synthetic import _quadratic1_fn

    spark = (
        pyspark.sql.SparkSession.builder.master("local[2]")
        .appName("hyperopt_tpu_test")
        .config("spark.ui.enabled", "false")
        .getOrCreate()
    )
    try:
        trials = SparkTrials(parallelism=2, spark_session=spark)
        best = fmin(
            _quadratic1_fn, hp.uniform("x", -5, 5), algo=rand.suggest,
            max_evals=6, trials=trials,
            rstate=np.random.default_rng(0), show_progressbar=False,
        )
        assert "x" in best
        trials.refresh()
        assert len(trials) == 6
        assert all(t["state"] == JOB_STATE_DONE for t in trials.trials)
        assert all(
            t["result"]["status"] == STATUS_OK for t in trials.trials
        )

        # timeout cancellation goes through the REAL cancelJobGroup
        slow_trials = SparkTrials(
            parallelism=1, timeout=1.0, spark_session=spark
        )

        def slow(x):
            import time as _t

            _t.sleep(30)
            return x**2

        fmin(
            slow, hp.uniform("x", -5, 5), algo=rand.suggest,
            max_evals=4, trials=slow_trials,
            rstate=np.random.default_rng(0), show_progressbar=False,
            return_argmin=False,
        )
        assert slow_trials._fmin_cancelled
        assert "timeout" in (slow_trials._fmin_cancelled_reason or "")
    finally:
        spark.stop()
