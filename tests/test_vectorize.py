"""Tests for batch sampling / sparse idxs-vals encoding (reference:
tests/test_vectorize.py behavior, SURVEY.md SS3.3)."""

import numpy as np
import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.pyll_utils import EQ, expr_to_config
from hyperopt_tpu.pyll.base import as_apply
from hyperopt_tpu.vectorize import (
    VectorizeHelper,
    dense_to_idxs_vals,
    idxs_vals_to_dense,
    pretty_names,
    sample_config,
)


def cond_space():
    return hp.choice(
        "root",
        [
            {"branch": "flat", "x": hp.uniform("x_flat", 0, 1)},
            {
                "branch": "deep",
                "y": hp.loguniform("y_deep", -3, 0),
                "sub": hp.choice("sub", [hp.normal("n0", 0, 1), hp.randint("r1", 4)]),
            },
        ],
    )


def test_expr_to_config_labels_and_conditions():
    hps = expr_to_config(as_apply(cond_space()))
    assert set(hps) == {"root", "x_flat", "y_deep", "sub", "n0", "r1"}
    assert hps["root"].unconditional
    assert hps["x_flat"].conditions == {(EQ("root", 0),)}
    assert hps["y_deep"].conditions == {(EQ("root", 1),)}
    assert hps["n0"].conditions == {(EQ("root", 1), EQ("sub", 0))}
    assert hps["root"].dist == "randint"
    assert hps["x_flat"].params == {"low": 0, "high": 1}


def test_expr_to_config_shared_param_merges_conditions():
    shared = hp.uniform("shared", 0, 1)
    space = hp.choice("c", [{"a": shared}, {"b": shared, "z": hp.normal("z", 0, 1)}])
    hps = expr_to_config(as_apply(space))
    assert hps["shared"].conditions == {(EQ("c", 0),), (EQ("c", 1),)}


def test_sample_batch_sparsity():
    helper = VectorizeHelper(cond_space())
    rng = np.random.default_rng(0)
    new_ids = list(range(50))
    idxs, vals = helper.sample_batch(new_ids, rng)
    # root is always active
    assert idxs["root"] == new_ids
    # each trial appears in exactly one of x_flat / y_deep
    flat = set(idxs["x_flat"])
    deep = set(idxs["y_deep"])
    assert flat | deep == set(new_ids)
    assert flat & deep == set()
    # deep trials all have a sub choice; n0/r1 partition them
    assert set(idxs["sub"]) == deep
    assert set(idxs["n0"]) | set(idxs["r1"]) == deep
    # drawn values respect bounds
    assert all(0 <= v <= 1 for v in vals["x_flat"])
    assert all(np.exp(-3) <= v <= 1.0 + 1e-12 for v in vals["y_deep"])
    assert all(v in range(4) for v in vals["r1"])
    # idxs_by_label view matches
    assert helper.idxs_by_label() == idxs


def test_sample_determinism():
    s1 = sample_config(cond_space(), np.random.default_rng(7))
    s2 = sample_config(cond_space(), np.random.default_rng(7))
    assert s1 == s2


def test_pretty_names():
    names = pretty_names(cond_space(), prefix="p")
    assert "p.root" in names.values()


def test_dense_sparse_roundtrip():
    labels = ["a", "b"]
    tids = [10, 11, 12]
    values = np.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]])
    active = np.array([[True, True, False], [False, True, True]])
    idxs, vals = dense_to_idxs_vals(tids, labels, values, active)
    assert idxs == {"a": [10, 11], "b": [11, 12]}
    assert vals == {"a": [1.0, 2.0], "b": [5.0, 6.0]}
    values2, active2 = idxs_vals_to_dense(tids, labels, idxs, vals)
    np.testing.assert_array_equal(active2, active)
    assert values2[0, 0] == 1.0 and values2[1, 2] == 6.0


def test_quantized_draws_are_quantized():
    space = {"q": hp.quniform("q", 0, 10, 0.5), "qi": hp.uniformint("qi", 0, 5)}
    cfgs = [sample_config(space, np.random.default_rng(i)) for i in range(30)]
    for c in cfgs:
        assert c["q"] == pytest.approx(round(c["q"] / 0.5) * 0.5)
        assert float(c["qi"]).is_integer()
        assert 0 <= c["qi"] <= 5
