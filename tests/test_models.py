"""Benchmark model-family tests: surrogate, nasbench, resnet population,
plus ATPE on them (BASELINE.json configs #3-#5)."""

import numpy as np
import pytest

from hyperopt_tpu import Trials, atpe, fmin, rand, tpe
from hyperopt_tpu.models import nasbench, surrogate


def test_surrogate_space_and_objective():
    from hyperopt_tpu.vectorize import sample_config
    from hyperopt_tpu.fmin import space_eval

    sp = surrogate.space()
    for seed in range(20):
        cfg_assign = sample_config(sp, np.random.default_rng(seed))
        cfg = space_eval(sp, cfg_assign)
        loss = surrogate.objective(cfg)
        assert 0.0 < loss < 2.0
        assert cfg["booster"] in ("gbtree", "dart")
        assert 2 <= cfg["max_depth"] <= 12


def test_tpe_on_surrogate_beats_random():
    def run(algo, seed):
        trials = Trials()
        fmin(
            surrogate.objective, surrogate.space(), algo=algo, max_evals=80,
            trials=trials, rstate=np.random.default_rng(seed),
            show_progressbar=False,
        )
        return trials.best_trial["result"]["loss"]

    tpe_best = min(run(tpe.suggest, s) for s in (0, 1))
    rand_best = min(run(rand.suggest, s) for s in (0, 1))
    assert tpe_best <= rand_best + 0.01
    assert tpe_best < surrogate.best_known() + 0.08


def test_nasbench_table_properties():
    archs, losses = nasbench.full_table()
    assert len(archs) == 5**6
    assert np.isfinite(losses).all()
    assert 4.0 < losses.min() < losses.max() < 50.0
    # same arch -> same loss (deterministic table)
    cfg = {f"edge{e}": 2 for e in range(6)}
    assert nasbench.objective(cfg) == nasbench.objective(dict(cfg))


def test_tpe_jax_on_nasbench():
    """Choice-heavy space through the jitted categorical posterior path."""
    from hyperopt_tpu import tpe_jax

    trials = Trials()
    fmin(
        nasbench.objective, nasbench.space(), algo=tpe_jax.suggest,
        max_evals=60, trials=trials, rstate=np.random.default_rng(0),
        show_progressbar=False, max_queue_len=8,
    )
    best = trials.best_trial["result"]["loss"]
    opt = nasbench.optimal_loss()
    # within 60 evals of a 15625-arch table, must land in the good tail
    _, losses = nasbench.full_table()
    assert best <= np.percentile(losses, 8)
    assert best >= opt - 1e-9


def test_atpe_runs_and_competes_on_quadratic():
    from hyperopt_tpu import hp

    def run(algo, seed):
        trials = Trials()
        fmin(
            lambda x: (x - 3.0) ** 2, hp.uniform("x", -10, 10), algo=algo,
            max_evals=70, trials=trials, rstate=np.random.default_rng(seed),
            show_progressbar=False,
        )
        return trials.best_trial["result"]["loss"]

    atpe_best = np.median([run(atpe.suggest, s) for s in (0, 1, 2)])
    rand_best = np.median([run(rand.suggest, s) for s in (0, 1, 2)])
    assert atpe_best <= rand_best + 1e-9
    assert atpe_best < 0.5


def test_atpe_conditional_space_structural_integrity():
    from hyperopt_tpu import hp

    space = hp.choice(
        "c",
        [
            {"kind": "a", "lr": hp.loguniform("lr_a", -5, 0)},
            {"kind": "b", "x": hp.uniform("x_b", 0, 1)},
        ],
    )

    def obj(cfg):
        return cfg["lr"] if cfg["kind"] == "a" else cfg["x"] + 0.2

    trials = Trials()
    fmin(
        obj, space, algo=atpe.suggest, max_evals=50, trials=trials,
        rstate=np.random.default_rng(1), show_progressbar=False,
    )
    for t in trials.trials:
        vals = t["misc"]["vals"]
        if vals["c"][0] == 0:
            assert vals["lr_a"] and not vals["x_b"]
        else:
            assert vals["x_b"] and not vals["lr_a"]


def test_atpe_locking_kicks_in():
    """After convergence, ATPE should lock converged dims at least once."""
    from hyperopt_tpu import hp
    from hyperopt_tpu.atpe import ATPEOptimizer
    from hyperopt_tpu.base import Domain

    domain = Domain(lambda cfg: 0.0, {"x": hp.uniform("x", 0, 1),
                                      "y": hp.uniform("y", -5, 5)})
    trials = Trials()
    docs = []
    rng = np.random.default_rng(0)
    ids = trials.new_trial_ids(40)
    for tid in ids:
        x = 0.5 + rng.normal(0, 0.001)  # x converged
        y = rng.uniform(-5, 5)          # y still exploring
        misc = {"tid": tid, "cmd": None,
                "idxs": {"x": [tid], "y": [tid]},
                "vals": {"x": [x], "y": [y]}}
        (d,) = trials.new_trial_docs(
            [tid], [None], [{"status": "ok", "loss": abs(y)}], [misc]
        )
        d["state"] = 2
        docs.append(d)
    trials.insert_trial_docs(docs)
    trials.refresh()
    opt = ATPEOptimizer(lock_fraction=1.0)
    locked = opt.locked_values(domain, trials, np.random.default_rng(1))
    assert "x" in locked and abs(locked["x"] - 0.5) < 0.01
    assert "y" not in locked


def test_atpe_no_locking_on_single_dim_space():
    """Locking may concentrate, never collapse: a 1-dim space must keep
    its only dim exploring (max_lock = D//2 = 0 -> no locks), matching
    the documented 'at least half the dims keep exploring' invariant."""
    from hyperopt_tpu import hp
    from hyperopt_tpu.atpe import ATPEOptimizer
    from hyperopt_tpu.base import Domain

    domain = Domain(lambda cfg: 0.0, {"x": hp.uniform("x", 0, 1)})
    trials = Trials()
    docs = []
    rng = np.random.default_rng(0)
    ids = trials.new_trial_ids(40)
    for tid in ids:
        x = 0.5 + rng.normal(0, 0.001)  # fully converged
        misc = {"tid": tid, "cmd": None,
                "idxs": {"x": [tid]}, "vals": {"x": [x]}}
        (d,) = trials.new_trial_docs(
            [tid], [None], [{"status": "ok", "loss": abs(x - 0.5)}], [misc]
        )
        d["state"] = 2
        docs.append(d)
    trials.insert_trial_docs(docs)
    trials.refresh()
    opt = ATPEOptimizer(lock_fraction=1.0)
    locked = opt.locked_values(domain, trials, np.random.default_rng(1))
    assert locked == {}


@pytest.mark.slow
def test_resnet_tiny_objective_lr_sensitivity():
    from hyperopt_tpu.models import resnet

    obj = resnet.population_objective(n_steps=2, batch_size=16, image_size=8)
    good = obj({"lr": 0.05, "wd": 1e-4})
    bad = obj({"lr": 1e-5, "wd": 1e-4})
    assert np.isfinite(good) and np.isfinite(bad)
    assert good < bad  # a sane lr must beat a vanishing one after 2 steps


@pytest.mark.slow
def test_transformer_objective_lr_sensitivity():
    from hyperopt_tpu.models import transformer

    obj = transformer.population_objective(n_steps=6)
    good = obj({"lr": 0.3, "wd": 1e-5})
    bad = obj({"lr": 1e-4, "wd": 1e-5})
    assert np.isfinite(good) and np.isfinite(bad)
    assert good < bad  # a sane lr must beat a vanishing one after 6 steps


@pytest.mark.slow
def test_transformer_population_sharded_step():
    """The transformer population trains with the population sharded over
    'trial' and the token batch over 'cand' on the 8-device mesh --
    the same GSPMD shape as the resnet family (config #4 twin)."""
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.models import transformer
    from hyperopt_tpu.parallel.mesh import mesh_from_spec

    mesh = mesh_from_spec((2, 4), ("trial", "cand"))
    model = transformer.TinyLM(vocab=16, d_model=16, n_heads=2,
                               n_layers=1, max_len=16)
    step = transformer.make_population_train_step(model, mesh=mesh)
    pop = 4
    params = transformer.init_population(
        model, pop, jax.random.key(0), seq_len=16
    )
    momentum = jax.tree.map(jnp.zeros_like, params)
    tokens = transformer.synthetic_token_batch(
        jax.random.key(1), batch_size=16, seq_len=16, vocab=16, n_deltas=4
    )
    lr = jnp.asarray([0.3, 0.1, 0.03, 0.01], jnp.float32)
    wd = jnp.full((pop,), 1e-5, jnp.float32)
    losses = []
    for _ in range(4):
        params, momentum, loss = step(params, momentum, lr, wd, tokens)
        losses.append(np.asarray(loss))
    assert np.isfinite(losses).all()
    # population members really differ (per-member lr) and training helps
    assert len(np.unique(np.round(losses[-1], 6))) > 1
    assert losses[-1].min() < losses[0].min()


@pytest.mark.slow
def test_atpe_jax_end_to_end():
    """Adaptive TPE over the device sweep: runs, beats random at median,
    locks respect conditional structure."""
    from hyperopt_tpu import atpe_jax, hp

    space = {
        "x": hp.uniform("x", -5.0, 5.0),
        "arch": hp.choice("arch", [
            {"k": 0, "depth": hp.randint("depth", 2, 8)},
            {"k": 1, "w": hp.uniform("w", 0.0, 1.0)},
        ]),
    }

    def obj(cfg):
        a = cfg["arch"]
        extra = 0.1 * (a["depth"] - 5) ** 2 if a["k"] == 0 else 1.0 + a["w"]
        return (cfg["x"] - 1.0) ** 2 + extra

    def run(algo, seed):
        trials = Trials()
        fmin(obj, space, algo=algo, max_evals=70, trials=trials,
             rstate=np.random.default_rng(seed), show_progressbar=False)
        for t in trials.trials:  # structural integrity under locking
            vals = t["misc"]["vals"]
            arm = vals["arch"][0]
            assert (len(vals["depth"]) == 1) == (arm == 0)
            assert (len(vals["w"]) == 1) == (arm == 1)
        return min(trials.losses())

    atpe_best = np.median([run(atpe_jax.suggest, s) for s in (0, 1, 2)])
    rand_best = np.median([run(rand.suggest, s) for s in (0, 1, 2)])
    assert atpe_best <= rand_best + 1e-9
    assert atpe_best < 1.0


def test_mixed_space_fn_jax_matches_host():
    """bench.py's device-loop 1k-trial metric evaluates the jnp twin of
    mixed_space_fn -- the two must agree on real sampled configs."""
    import jax.numpy as jnp

    from hyperopt_tpu.fmin import space_eval
    from hyperopt_tpu.models.synthetic import (
        mixed_space, mixed_space_fn, mixed_space_fn_jax,
    )
    from hyperopt_tpu.vectorize import sample_config

    sp = mixed_space()
    cfgs = [
        space_eval(sp, sample_config(sp, np.random.default_rng(s)))
        for s in range(32)
    ]
    host = np.array([mixed_space_fn(c) for c in cfgs])
    batch = {k: jnp.array([float(c[k]) for c in cfgs]) for k in cfgs[0]}
    dev = np.asarray(mixed_space_fn_jax(batch))
    assert np.allclose(host, dev, atol=1e-4)


@pytest.mark.slow
def test_atpe_jax_not_worse_than_tpe_on_surrogate():
    """VERDICT round-2 evidence test: adaptive TPE must EARN its name --
    on the HPOBench-style mixed surrogate its online adaptation
    (continuous candidate scaling, per-family counts, capped locking)
    beats plain tpe_jax (full 5-seed measurement in BASELINE.md's ATPE
    table: 0.0502 vs 0.0543 at 150 evals; this CI-sized version measured
    0.0594 vs 0.0657).  Deterministic at fixed seeds."""
    from hyperopt_tpu import atpe_jax, tpe_jax

    def run(algo, seed):
        trials = Trials()
        fmin(surrogate.objective, surrogate.space(), algo=algo,
             max_evals=100, trials=trials,
             rstate=np.random.default_rng(seed), show_progressbar=False,
             return_argmin=False)
        return float(min(trials.losses()))

    tpe_med = np.median([run(tpe_jax.suggest, s) for s in (0, 1, 2)])
    atpe_med = np.median([run(atpe_jax.suggest, s) for s in (0, 1, 2)])
    assert atpe_med <= tpe_med + 0.005, (atpe_med, tpe_med)
    assert atpe_med < 0.075


def test_atpe_pure_categorical_falls_back_to_plain_tpe():
    """On pure-categorical spaces every ATPE lever measured
    neutral-to-harmful (BASELINE.md), so the optimizer must emit plain
    TPE settings and an empty lock set there."""
    from hyperopt_tpu.atpe import ATPEOptimizer
    from hyperopt_tpu.base import Domain, JOB_STATE_DONE
    from hyperopt_tpu import rand

    domain = Domain(nasbench.objective, nasbench.space())
    trials = Trials()
    docs = rand.suggest(trials.new_trial_ids(30), domain, trials, seed=0)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
        cfg = {k: v[0] for k, v in doc["misc"]["vals"].items()}
        doc["result"] = {"status": "ok", "loss": nasbench.objective(cfg)}
    trials.insert_trial_docs(docs)
    trials.refresh()

    opt = ATPEOptimizer(base_n_ei=128)
    settings = opt.tpe_settings(domain, trials)
    assert settings == {
        "gamma": 0.25,
        "n_EI_candidates": 128,
        "prior_weight": 1.0,
        "n_EI_candidates_cat": 24,
        "explore_fraction": 0.0,  # restarts never fire on pure-cat spaces
    }
    assert opt.lock_candidates(domain, trials) == {}


def _trials_with_losses(domain, losses):
    """A completed history over domain's space with the given losses."""
    from hyperopt_tpu import rand
    from hyperopt_tpu.base import JOB_STATE_DONE

    trials = Trials()
    docs = rand.suggest(
        trials.new_trial_ids(len(losses)), domain, trials, seed=0
    )
    for doc, loss in zip(docs, losses):
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float(loss)}
    trials.insert_trial_docs(docs)
    trials.refresh()
    return trials


def test_atpe_stall_detector_fires_and_clears():
    """Round-3 stall lever: a best-loss curve that has gone flat (recent
    gain <= 2% of total gain over the last ~15 trials) flips the
    settings to re-exploration (prior boost + restart fraction); an
    improving curve keeps sharpening instead.  The old detector
    (gain <= 1e-6 relative) never fired on smooth objectives -- this
    pins the one that does."""
    from hyperopt_tpu import hp
    from hyperopt_tpu.atpe import ATPEOptimizer
    from hyperopt_tpu.base import Domain

    domain = Domain(lambda c: 0.0, {
        "x": hp.uniform("x", 0, 1), "y": hp.uniform("y", -5, 5),
    })
    opt = ATPEOptimizer()

    # stalled: early improvement, then 30 trials with no new best
    stalled = list(np.linspace(10.0, 1.0, 10)) + [5.0] * 30
    s = opt.tpe_settings(domain, _trials_with_losses(domain, stalled))
    assert s["prior_weight"] == 1.5
    assert s["explore_fraction"] == 0.25

    # improving: fresh bests keep arriving through the tail
    improving = list(np.linspace(10.0, 1.0, 40))
    s = opt.tpe_settings(domain, _trials_with_losses(domain, improving))
    assert s["prior_weight"] == 1.0
    assert s["explore_fraction"] == 0.0
    assert s["gamma"] < 0.22  # sharpened


@pytest.mark.slow
def test_atpe_jax_trap15_quality():
    """The round-3 stall battery config (deceptive multi-basin trap15):
    ATPE with the stall lever must comfortably beat random's ~0.30
    median (calibration @150 evals, 3 seeds: atpe 0.204-0.259, median
    0.237).  The measured verdict vs plain TPE is parity (~2% -- see
    BASELINE.md round-3 ATPE section for why: the Parzen prior component
    is already a persistent exploration mechanism), so the bar pins
    beats-random plus the no-harm floor, not a TPE win."""
    from hyperopt_tpu import atpe_jax, fmin
    from hyperopt_tpu.models.synthetic import DOMAINS

    d = DOMAINS["trap15"]
    outs = []
    for seed in (0, 1, 2):
        trials = Trials()
        fmin(d.fn, d.make_space(), algo=atpe_jax.suggest, max_evals=150,
             trials=trials, rstate=np.random.default_rng(seed),
             show_progressbar=False, return_argmin=False)
        outs.append(min(trials.losses()))
    assert float(np.median(outs)) <= 0.285, outs


def test_atpe_meta_model_hook_gets_final_say():
    """The reference ATPE's pretrained meta-models are exposed here as
    ATPEOptimizer(meta_model=...); the hook must be consulted on every
    space -- including pure-categorical ones, where the built-in
    heuristics fall back to plain TPE settings first."""
    from hyperopt_tpu import rand
    from hyperopt_tpu.atpe import ATPEOptimizer
    from hyperopt_tpu.base import Domain, JOB_STATE_DONE
    from hyperopt_tpu import hp

    calls = []

    def meta(n_dims, frac_cat, n, gamma, n_ei, prior_weight):
        calls.append((n_dims, round(frac_cat, 3), n, gamma, n_ei))
        return 0.19, 77, 1.25

    def seeded_trials(domain, n=25):
        trials = Trials()
        docs = rand.suggest(trials.new_trial_ids(n), domain, trials, seed=0)
        trials.insert_trial_docs(docs)
        trials.refresh()
        for d in trials._dynamic_trials:
            d["state"] = JOB_STATE_DONE
            d["result"] = {"status": "ok", "loss": 1.0}
        trials.refresh()
        return trials

    opt = ATPEOptimizer(meta_model=meta, base_n_ei=128)

    # mixed space: heuristics compute, meta overrides
    dom_mixed = Domain(lambda c: 0.0, {
        "x": hp.uniform("x", 0, 1), "k": hp.choice("k", [0, 1, 2]),
    })
    s = opt.tpe_settings(dom_mixed, seeded_trials(dom_mixed))
    assert (s["gamma"], s["n_EI_candidates"], s["prior_weight"]) == (
        0.19, 77, 1.25
    )

    # pure-categorical space: heuristic fallback, meta STILL consulted
    dom_cat = Domain(nasbench.objective, nasbench.space())
    s = opt.tpe_settings(dom_cat, seeded_trials(dom_cat))
    assert (s["gamma"], s["n_EI_candidates"], s["prior_weight"]) == (
        0.19, 77, 1.25
    )
    assert len(calls) == 2
    # the heuristic inputs handed to the meta model reflect each space
    assert calls[0][0] == 2 and calls[1][0] == 6
