"""Unit tests for Trials / Domain / miscs helpers (reference:
tests/test_base.py + test_trials.py, SURVEY.md SS4)."""

import numpy as np
import pytest

from hyperopt_tpu import (
    Ctrl,
    Domain,
    JOB_STATE_DONE,
    JOB_STATE_NEW,
    STATUS_OK,
    STATUS_FAIL,
    Trials,
    hp,
    trials_from_docs,
)
from hyperopt_tpu.base import (
    SONify,
    miscs_to_idxs_vals,
    miscs_update_idxs_vals,
    spec_from_misc,
)
from hyperopt_tpu.exceptions import (
    AllTrialsFailed,
    DuplicateLabel,
    InvalidLoss,
    InvalidResultStatus,
    InvalidTrial,
)


def test_package_surface_reaches_every_documented_submodule():
    """Every submodule the docs tell users to reach as an attribute
    (``hyperopt_tpu.hyperband``, ``.pbt``, ...) must resolve through
    the package's lazy loader -- a module missing from the lazy set is
    importable as ``from hyperopt_tpu.X import ...`` but raises on
    attribute access, a silent API-surface gap."""
    import hyperopt_tpu as h

    for name in (
        "tpe_jax", "rand_jax", "anneal_jax", "atpe_jax", "device_loop",
        "jax_trials", "ops", "parallel", "distributed", "models",
        "hyperband", "pbt", "atpe", "criteria", "plotting", "graphviz",
        "vectorize", "pyll_utils", "early_stop", "tpe", "rand", "mix",
        "anneal", "pyll", "utils", "base", "exceptions", "progress",
    ):
        mod = getattr(h, name)
        assert mod is not None, name
    assert callable(h.hyperband.asha)
    assert callable(h.pbt.compile_pbt)
    assert callable(h.device_loop.compile_fmin)


def make_doc(trials, tid, loss, state=JOB_STATE_DONE, status=STATUS_OK, label="x"):
    misc = {"tid": tid, "cmd": None, "idxs": {label: [tid]}, "vals": {label: [0.5]}}
    (doc,) = trials.new_trial_docs(
        [tid], [None], [{"status": status, "loss": loss}], [misc]
    )
    doc["state"] = state
    return doc


def test_insert_and_query():
    trials = Trials()
    docs = [make_doc(trials, tid, loss) for tid, loss in zip(range(3), [3.0, 1.0, 2.0])]
    trials.insert_trial_docs(docs)
    trials.refresh()
    assert len(trials) == 3
    assert trials.losses() == [3.0, 1.0, 2.0]
    assert trials.statuses() == [STATUS_OK] * 3
    assert trials.best_trial["tid"] == 1
    assert trials.argmin == {"x": 0.5}
    assert trials.tids == [0, 1, 2]


def test_new_trial_ids_monotonic():
    trials = Trials()
    a = trials.new_trial_ids(3)
    b = trials.new_trial_ids(2)
    assert a == [0, 1, 2]
    assert b == [3, 4]


def test_validation_rejects_garbage():
    trials = Trials()
    with pytest.raises(InvalidTrial):
        trials.insert_trial_doc({"tid": 0})
    with pytest.raises(InvalidTrial):
        trials.insert_trial_doc("not-a-dict")


def test_validation_tid_mismatch():
    trials = Trials()
    doc = make_doc(trials, 0, 1.0)
    doc["misc"]["tid"] = 99
    with pytest.raises(InvalidTrial):
        trials.insert_trial_doc(doc)


def test_all_trials_failed():
    trials = Trials()
    doc = make_doc(trials, 0, None, status=STATUS_FAIL)
    doc["result"] = {"status": STATUS_FAIL}
    trials.insert_trial_docs([doc])
    trials.refresh()
    with pytest.raises(AllTrialsFailed):
        trials.best_trial


def test_exp_key_filtering():
    trials = Trials(exp_key="A")
    doc = make_doc(trials, 0, 1.0)
    doc["exp_key"] = "A"
    other = make_doc(trials, 1, 2.0)
    other["exp_key"] = "B"
    trials._insert_trial_docs([doc, other])
    trials.refresh()
    assert len(trials) == 1
    view = trials.view(exp_key="B")
    assert len(view) == 1
    view_all = trials.view(exp_key=None)
    assert len(view_all) == 2


def test_count_by_state():
    trials = Trials()
    d0 = make_doc(trials, 0, 1.0, state=JOB_STATE_NEW)
    d1 = make_doc(trials, 1, 2.0, state=JOB_STATE_DONE)
    trials.insert_trial_docs([d0, d1])
    trials.refresh()
    assert trials.count_by_state_synced(JOB_STATE_NEW) == 1
    assert trials.count_by_state_unsynced([JOB_STATE_NEW, JOB_STATE_DONE]) == 2


def test_trials_from_docs_roundtrip():
    trials = Trials()
    docs = [make_doc(trials, tid, float(tid)) for tid in range(3)]
    trials2 = trials_from_docs(docs)
    assert len(trials2) == 3
    assert trials2.argmin == {"x": 0.5}


def test_miscs_to_idxs_vals_roundtrip():
    miscs = [
        {"tid": 0, "cmd": None, "idxs": {"x": [0], "y": []}, "vals": {"x": [1.5], "y": []}},
        {"tid": 1, "cmd": None, "idxs": {"x": [1], "y": [1]}, "vals": {"x": [2.5], "y": [7]}},
    ]
    idxs, vals = miscs_to_idxs_vals(miscs)
    assert idxs == {"x": [0, 1], "y": [1]}
    assert vals == {"x": [1.5, 2.5], "y": [7]}
    # scatter back
    blank = [
        {"tid": 0, "cmd": None, "idxs": {}, "vals": {}},
        {"tid": 1, "cmd": None, "idxs": {}, "vals": {}},
    ]
    miscs_update_idxs_vals(blank, idxs, vals)
    assert blank[0]["vals"] == {"x": [1.5], "y": []}
    assert blank[1]["vals"] == {"x": [2.5], "y": [7]}


def test_spec_from_misc():
    misc = {"tid": 0, "cmd": None, "idxs": {"x": [0], "y": []}, "vals": {"x": [4.0], "y": []}}
    assert spec_from_misc(misc) == {"x": 4.0}


def test_sonify():
    out = SONify(
        {"a": np.int64(3), "b": np.float32(1.5), "c": np.arange(3), "d": [np.bool_(True)]}
    )
    assert out == {"a": 3, "b": 1.5, "c": [0, 1, 2], "d": [True]}
    assert type(out["a"]) is int
    assert type(out["b"]) is float


def test_domain_evaluate_float_and_dict():
    domain = Domain(lambda x: x**2, hp.uniform("x", -1, 1))
    trials = Trials()
    ctrl = Ctrl(trials)
    res = domain.evaluate({"x": 3.0}, ctrl)
    assert res == {"status": STATUS_OK, "loss": 9.0}

    domain2 = Domain(
        lambda x: {"loss": x + 1, "status": STATUS_OK, "extra": "kept"},
        hp.uniform("x", -1, 1),
    )
    res2 = domain2.evaluate({"x": 1.0}, ctrl)
    assert res2["loss"] == 2.0 and res2["extra"] == "kept"


def test_domain_evaluate_nan_is_fail():
    domain = Domain(lambda x: float("nan"), hp.uniform("x", -1, 1))
    res = domain.evaluate({"x": 0.0}, Ctrl(Trials()))
    assert res["status"] == STATUS_FAIL


def test_domain_invalid_status():
    domain = Domain(lambda x: {"status": "bogus"}, hp.uniform("x", -1, 1))
    with pytest.raises(InvalidResultStatus):
        domain.evaluate({"x": 0.0}, Ctrl(Trials()))


def test_domain_missing_loss():
    domain = Domain(lambda x: {"status": STATUS_OK}, hp.uniform("x", -1, 1))
    with pytest.raises(InvalidLoss):
        domain.evaluate({"x": 0.0}, Ctrl(Trials()))


def test_domain_duplicate_label():
    space = [hp.uniform("same", 0, 1), hp.normal("same", 0, 1)]
    with pytest.raises(DuplicateLabel):
        Domain(lambda cfg: 0.0, space)


def test_domain_conditional_evaluate():
    space = hp.choice(
        "c",
        [
            {"kind": "a", "val": hp.uniform("ua", 0, 1)},
            {"kind": "b", "val": hp.uniform("ub", 5, 6)},
        ],
    )
    domain = Domain(lambda cfg: cfg["val"], space)
    res = domain.evaluate({"c": 1, "ub": 5.5}, Ctrl(Trials()))
    assert res["loss"] == 5.5


def test_trial_attachments():
    trials = Trials()
    doc = make_doc(trials, 0, 1.0)
    trials.insert_trial_docs([doc])
    trials.refresh()
    att = trials.trial_attachments(trials.trials[0])
    att["blob"] = b"\x00\x01"
    assert att["blob"] == b"\x00\x01"
    assert "blob" in att


def test_average_best_error():
    trials = Trials()
    docs = []
    for tid, loss in enumerate([1.0, 0.5, 2.0]):
        docs.append(make_doc(trials, tid, loss))
    trials.insert_trial_docs(docs)
    trials.refresh()
    assert trials.average_best_error() == pytest.approx(0.5)
