"""Unit pins for the write-ahead tell log (utils/wal.py): record
round-trip, checksum enforcement, the torn-tail truncation rule,
monotone counters across compaction, and guard refusal -- the
primitives the resume-parity suite (test_resume_parity.py) composes."""

import os

import numpy as np
import pytest

from hyperopt_tpu.distributed.faults import FaultPlan
from hyperopt_tpu.exceptions import CheckpointError
from hyperopt_tpu.utils.checkpoint import decode_rstate, encode_rstate
from hyperopt_tpu.utils.wal import TellWAL


def test_append_replay_roundtrip(tmp_path):
    path = str(tmp_path / "w.wal")
    wal = TellWAL(path, guard=["g", 1])
    s0 = wal.append("ask", {"docs": [{"tid": 0}], "rstate": {"k": 1}})
    s1 = wal.append("tell", {"tid": 0, "state": 2,
                             "result": {"status": "ok", "loss": 0.5}})
    assert (s0, s1) == (0, 1)
    wal.close()

    fresh = TellWAL(path, guard=["g", 1])
    records = fresh.replay()
    assert [r["kind"] for r in records] == ["ask", "tell"]
    assert records[0]["docs"] == [{"tid": 0}]
    assert records[1]["result"]["loss"] == 0.5
    assert fresh.next_seq == 2
    assert fresh.total_tells == 1


def test_torn_tail_is_truncated_not_fatal(tmp_path):
    path = str(tmp_path / "w.wal")
    wal = TellWAL(path)
    for i in range(5):
        wal.append("tell", {"tid": i, "state": 2})
    wal.close()
    good_size = os.path.getsize(path)
    # a torn append: half a record, no trailing newline
    with open(path, "a") as f:
        f.write('deadbeef {"seq": 5, "kind": "tell", "tid": 99')
    fresh = TellWAL(path)
    records = fresh.replay()
    assert [r["tid"] for r in records] == [0, 1, 2, 3, 4]
    assert os.path.getsize(path) == good_size  # tail truncated in place
    # appends continue from the valid prefix
    assert fresh.append("tell", {"tid": 5, "state": 2}) == 5
    assert fresh.total_tells == 6


def test_torn_binary_garbage_tail(tmp_path):
    path = str(tmp_path / "w.wal")
    wal = TellWAL(path)
    wal.append("tell", {"tid": 0, "state": 2})
    wal.close()
    with open(path, "ab") as f:
        f.write(b"\xff\xfe\x00garbage")
    fresh = TellWAL(path)
    assert [r["tid"] for r in fresh.replay()] == [0]


def test_midfile_corruption_is_refused(tmp_path):
    path = str(tmp_path / "w.wal")
    wal = TellWAL(path)
    for i in range(3):
        wal.append("tell", {"tid": i, "state": 2})
    wal.close()
    lines = open(path).read().splitlines(keepends=True)
    lines[1] = "00000000 " + lines[1].split(" ", 1)[1]  # bad crc mid-file
    with open(path, "w") as f:
        f.write("".join(lines))
    with pytest.raises(CheckpointError, match="not a torn tail"):
        TellWAL(path).replay()


def test_reset_compacts_but_counters_survive(tmp_path):
    path = str(tmp_path / "w.wal")
    wal = TellWAL(path, guard=["g"])
    for i in range(4):
        wal.append("tell", {"tid": i, "state": 2})
    wal.reset()
    assert wal.replay() == []  # records absorbed
    assert wal.total_tells == 4  # ...but the monotone counter survives
    assert wal.append("tell", {"tid": 4, "state": 2}) == 4  # seq monotone
    fresh = TellWAL(path, guard=["g"])
    assert fresh.total_tells == 5
    assert fresh.next_seq == 5


def test_guard_mismatch_refused(tmp_path):
    path = str(tmp_path / "w.wal")
    wal = TellWAL(path, guard=["study-A"])
    wal.append("tell", {"tid": 0, "state": 2})
    wal.close()
    with pytest.raises(CheckpointError, match="different study"):
        TellWAL(path, guard=["study-B"]).replay()
    # no guard = no opinion (fsck reads logs without study context)
    assert len(TellWAL(path).replay()) == 1


def test_injected_partial_write_behaves_as_torn_tail(tmp_path):
    """A FaultPlan partial write mid-append is exactly the torn-tail
    case: the prefix survives, the torn record is truncated away."""
    path = str(tmp_path / "w.wal")
    wal = TellWAL(path)
    for i in range(3):
        wal.append("tell", {"tid": i, "state": 2})
    wal.close()
    plan = FaultPlan(seed=3, partial_rate=1.0, burst=1)
    faulty = TellWAL(path, fs=plan.fs())
    try:
        faulty.append("tell", {"tid": 3, "state": 2})
    except OSError:
        pass  # the injected EIO mid-record
    faulty.close()
    fresh = TellWAL(path)
    tids = [r["tid"] for r in fresh.replay()]
    assert tids[:3] == [0, 1, 2]  # prefix intact, tail (if torn) dropped
    assert plan.stats["error:partial_write"] >= 1


def test_rstate_cursor_roundtrip_reproduces_stream():
    rng = np.random.default_rng(123)
    rng.integers(2**31 - 1)  # advance
    cursor = encode_rstate(rng)
    import json

    cursor = json.loads(json.dumps(cursor))  # must survive JSON
    expected = [int(rng.integers(2**31 - 1)) for _ in range(5)]
    restored = decode_rstate(cursor)
    assert [int(restored.integers(2**31 - 1)) for _ in range(5)] == expected


def test_rstate_cursor_roundtrip_legacy_randomstate():
    import json

    rs = np.random.RandomState(7)
    rs.randint(2**31 - 1)
    cursor = json.loads(json.dumps(encode_rstate(rs)))
    expected = [int(rs.randint(2**31 - 1)) for _ in range(5)]
    restored = decode_rstate(cursor)
    assert [int(restored.randint(2**31 - 1)) for _ in range(5)] == expected
