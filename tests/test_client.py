"""graftclient: fmin as a serve client (ISSUE 15).

The acceptance contract, pinned deterministically:

* K=1 BITWISE PARITY: ``fmin(engine=True)`` produces exactly the trial
  stream (tids, misc vals, losses, doc shapes) of the solo fused
  driver -- for tpe, anneal, AND atpe (host-hook dispatch);
* DEPTH INVISIBILITY: ``ask_ahead=k`` for any k>1 produces the SAME
  stream as k=1 (submit-time seeds + the study's fresh_window gate --
  the bitwise-at-any-depth construction);
* BACKPRESSURE IS A PACE SIGNAL: a typed ``Overloaded`` at submit
  becomes bounded retry-with-backoff under the client deadline, with a
  typed ``DeadlineExpired`` escalation -- never a full-timeout hang;
* CRASH-RECOVERY PARITY: kill-and-resume at every serve crash point
  reproduces the PR-6 driver guarantees through the ONE unified WAL
  (resume bitwise, zero lost / zero duplicate tells, durable failures
  never re-run);
* OBSERVABILITY: ``driver.trial`` spans carry the client-path study id
  end to end (they correlate with the serve ``ask.*`` spans).
"""

import os
import threading
import time

import numpy as np
import pytest

from hyperopt_tpu import anneal_jax, atpe_jax, fmin, hp, tpe_jax
from hyperopt_tpu.base import STATUS_FAIL, Trials
from hyperopt_tpu.client import CLIENT_STUDY, resolve_engine_algo
from hyperopt_tpu.distributed.faults import (
    SERVE_CRASH_POINTS,
    FaultPlan,
    SimulatedCrash,
)
from hyperopt_tpu.exceptions import (
    CheckpointError,
    DeadlineExpired,
    Overloaded,
)
from hyperopt_tpu.fmin import partial
from hyperopt_tpu.serve import SuggestService


@pytest.fixture(autouse=True)
def _lockdep_armed(monkeypatch):
    # the lockdep sanitizer rides every client scenario: each fmin
    # builds a scheduler, each instrumented; an observed lock-order
    # inversion raises at acquisition time
    from hyperopt_tpu.analysis import lockdep

    dep = lockdep.arm_scheduler_class(monkeypatch)
    yield dep
    assert dep.inversions == 0, dep.errors


SPACE = {
    "x": hp.uniform("x", -5, 5),
    "lr": hp.loguniform("lr", -5, 0),
    "q": hp.quniform("q", 0, 10, 1),
    "c": hp.choice("c", [0, 1, 2]),
}

# the serve test-suite algo parameters, expressed at the plugin seam
TPE_KW = dict(n_EI_candidates=16, n_EI_candidates_cat=8,
              n_startup_jobs=3)
N_FAST = 44  # past the warm boundary (3) and atpe's judged-at-20 gate


def objective(cfg):
    return (
        (cfg["x"] - 1) ** 2 / 10
        + abs(float(np.log(cfg["lr"])) + 2) / 3
        + abs(cfg["q"] - 4) / 5
        + 0.1 * cfg["c"]
    )


def run_fmin(algo, n=N_FAST, seed=7, obj=objective, trials=None, **kw):
    trials = Trials() if trials is None else trials
    fmin(
        obj, SPACE, algo=algo, max_evals=n, trials=trials,
        rstate=np.random.default_rng(seed), show_progressbar=False,
        return_argmin=False, **kw,
    )
    return trials


def stream(trials):
    """The comparison stream: everything deterministic about a doc."""
    return [
        (
            t["tid"], t["state"], t["misc"]["idxs"], t["misc"]["vals"],
            t["result"],
        )
        for t in trials._dynamic_trials
    ]


_REF_CACHE = {}


def solo_reference(key, algo, **kw):
    """The solo-driver reference stream, computed once per config."""
    if key not in _REF_CACHE:
        _REF_CACHE[key] = stream(run_fmin(algo, **kw))
    return _REF_CACHE[key]


# ---------------------------------------------------------------------------
# k=1 bitwise parity + depth invisibility
# ---------------------------------------------------------------------------


def test_tpe_client_k1_bitwise_vs_fused_solo():
    """The degenerate contract: fmin-as-client at k=1 is bitwise the
    solo fused one-dispatch-per-trial driver."""
    ref = solo_reference(
        "tpe-fused", partial(tpe_jax.suggest, fused=True, **TPE_KW)
    )
    got = stream(run_fmin(
        partial(tpe_jax.suggest, **TPE_KW), engine=True
    ))
    assert got == ref


def test_tpe_client_depth_is_invisible_to_the_stream():
    """ask_ahead=k for k>1: submit-time seeds fix the seed sequence
    and the fresh_window gate holds each dispatch until the posterior
    is full -- so ANY depth produces the k=1 (= solo) stream."""
    ref = solo_reference(
        "tpe-fused", partial(tpe_jax.suggest, fused=True, **TPE_KW)
    )
    for k in (2, 5):
        got = stream(run_fmin(
            partial(tpe_jax.suggest, **TPE_KW), ask_ahead=k
        ))
        assert got == ref, f"ask_ahead={k} perturbed the stream"


def test_tpe_client_k1_bitwise_vs_reupload_solo():
    """The re-upload (non-resident) solo driver is bitwise the fused
    one (PR-4 pin), so the client matches it too -- pinned directly."""
    ref = solo_reference(
        "tpe-plain", partial(tpe_jax.suggest, **TPE_KW)
    )
    got = stream(run_fmin(
        partial(tpe_jax.suggest, **TPE_KW), engine=True
    ))
    assert got == ref


def test_anneal_client_k1_and_depth_parity():
    ref = solo_reference(
        "anneal-res", partial(anneal_jax.suggest, resident=True)
    )
    assert stream(run_fmin(anneal_jax.suggest, engine=True)) == ref
    assert stream(run_fmin(anneal_jax.suggest, ask_ahead=3)) == ref


def test_atpe_client_k1_and_depth_parity():
    """atpe rides the client API through its per-study host_algo hook
    (the host decision layer cannot vmap across studies) -- stream
    bitwise the solo adaptive driver, at any depth."""
    ref = solo_reference(
        "atpe", partial(atpe_jax.suggest, n_startup_jobs=3)
    )
    assert stream(run_fmin(
        partial(atpe_jax.suggest, n_startup_jobs=3), engine=True
    )) == ref
    # depth >1 for atpe rides the generic gate already pinned above
    # and in the slow 200-trial sweep (fast-tier wall-clock budget)


def test_client_containment_matches_solo():
    """catch= / trial_timeout containment and non-finite quarantine
    behave identically through the client (the shared _evaluate_trial
    machinery + fail records instead of posterior tells)."""

    def flaky(cfg):
        if cfg["c"] == 2:
            raise ValueError("boom")
        if cfg["q"] == 0.0:
            return float("nan")
        return objective(cfg)

    kw = dict(obj=flaky, catch=(ValueError,))
    ref = stream(run_fmin(
        partial(tpe_jax.suggest, fused=True, **TPE_KW), **kw
    ))
    got = stream(run_fmin(
        partial(tpe_jax.suggest, **TPE_KW), engine=True, **kw
    ))
    assert got == ref
    assert any(t[4].get("status") == STATUS_FAIL for t in got)


@pytest.mark.slow
def test_client_parity_200_trials_all_algos():
    """The 200-trial acceptance sweep: past the pow2 bucket crossing
    and the _grow capacity boundary, for every engine algo, at two
    depths, against BOTH solo variants (resident + re-upload)."""
    cases = [
        ("tpe", partial(tpe_jax.suggest, **TPE_KW),
         partial(tpe_jax.suggest, fused=True, **TPE_KW)),
        ("anneal", anneal_jax.suggest,
         partial(anneal_jax.suggest, resident=True)),
        ("atpe", partial(atpe_jax.suggest, n_startup_jobs=3),
         partial(atpe_jax.suggest, resident=True, n_startup_jobs=3)),
    ]
    for name, plain_algo, resident_algo in cases:
        ref_plain = stream(run_fmin(plain_algo, n=200))
        ref_res = stream(run_fmin(resident_algo, n=200))
        assert ref_plain == ref_res, f"{name}: solo variants diverged"
        for k in (1, 4):
            got = stream(run_fmin(plain_algo, n=200, ask_ahead=k))
            assert got == ref_plain, f"{name} diverged at depth {k}"


# ---------------------------------------------------------------------------
# backpressure: Overloaded -> bounded retry -> DeadlineExpired
# ---------------------------------------------------------------------------


def _tiny_service(**kw):
    return SuggestService(
        SPACE, background=False, n_startup_jobs=2, n_cand=8,
        n_cand_cat=8, **kw,
    )


def test_overloaded_backoff_retries_until_served():
    """A full queue refuses the submit with Overloaded(retry_after);
    ask(backoff=True) sleeps the hint and retries -- once a round
    drains the queue, the ask is admitted and served."""
    svc = _tiny_service(max_queue=1)
    a = svc.create_study("a", seed=1)
    b = svc.create_study("b", seed=2)
    a.ask_async()  # fills the bounded queue
    with pytest.raises(Overloaded):
        b.ask(timeout=0.2)  # without backoff: the typed refusal

    drained = threading.Event()

    def drain():
        time.sleep(0.1)
        svc.pump()  # picks the queued ask -> queue has room again
        drained.set()

    t = threading.Thread(target=drain)
    t.start()
    tid, vals = b.ask(timeout=10.0, backoff=True)
    t.join()
    assert drained.is_set()
    assert tid == 0 and isinstance(vals, dict) and vals
    assert svc.scheduler.shed_count >= 1
    svc.shutdown()


def test_overloaded_backoff_escalates_to_deadline_expired():
    """No drain ever comes: the bounded retry must escalate with the
    typed DeadlineExpired at (not after) the client deadline -- never
    a stuck full-timeout hang."""
    svc = _tiny_service(max_queue=1)
    a = svc.create_study("a", seed=1)
    b = svc.create_study("b", seed=2)
    a.ask_async()
    t0 = time.perf_counter()
    with pytest.raises(DeadlineExpired):
        b.ask(timeout=0.3, backoff=True)
    assert time.perf_counter() - t0 < 5.0  # escalated, did not hang
    svc.shutdown()


# ---------------------------------------------------------------------------
# engine-arg validation
# ---------------------------------------------------------------------------


def test_unmappable_algos_are_refused_loudly():
    from hyperopt_tpu import tpe

    with pytest.raises(ValueError, match="cannot route"):
        resolve_engine_algo(tpe.suggest)
    with pytest.raises(ValueError, match="speculative"):
        resolve_engine_algo(partial(tpe_jax.suggest, speculative=8))
    with pytest.raises(ValueError, match="joint_ei"):
        resolve_engine_algo(partial(tpe_jax.suggest, joint_ei=True))
    with pytest.raises(ValueError, match="max_queue_len"):
        run_fmin(tpe_jax.suggest, n=2, engine=True, max_queue_len=4)


def test_legacy_checkpoint_file_is_refused(tmp_path):
    legacy = tmp_path / "ckpt.pkl"
    legacy.write_bytes(b"not a study root")
    with pytest.raises(CheckpointError, match="DIRECTORY"):
        run_fmin(
            tpe_jax.suggest, n=2, engine=True,
            trials_save_file=str(legacy),
        )


# ---------------------------------------------------------------------------
# unified durability: resume, crash points, fail records, fsck
# ---------------------------------------------------------------------------


def _client_service(root, fs, k=1):
    return SuggestService(
        SPACE, root=root, fs=fs, background=False, max_batch=1,
        n_startup_jobs=3, snapshot_cadence=4, finite_check=False,
        study_queue_cap=max(2, k), max_queue=max(8, 2 * k),
        n_cand=16, n_cand_cat=8,
    )


CLIENT_ALGO = partial(tpe_jax.suggest, **TPE_KW)
N_CHAOS = 14


def _chaos_reference():
    return solo_reference(
        "chaos-ref",
        partial(tpe_jax.suggest, fused=True, **TPE_KW),
        n=N_CHAOS, seed=3,
    )


@pytest.mark.parametrize("point", SERVE_CRASH_POINTS)
@pytest.mark.parametrize("depth", [1, 3])
def test_kill_and_resume_at_serve_crash_points(tmp_path, point, depth):
    """Kill the client at every serve crash point (tell durable but
    unapplied / batch assembled / dispatched-unacked), resume over the
    same root: the finished stream is bitwise the uninterrupted solo
    run's, with zero lost and zero duplicate tells -- the PR-6 driver
    guarantees through the unified serve WAL."""
    ref = _chaos_reference()
    root = str(tmp_path / f"{point}-{depth}")
    plan = FaultPlan(seed=11)
    plan.arm(point, at=5)
    svc = _client_service(root, plan.fs(), k=depth)
    n_crashes = 0
    try:
        run_fmin(CLIENT_ALGO, n=N_CHAOS, seed=3, engine=svc,
                 ask_ahead=depth)
    except SimulatedCrash:
        n_crashes += 1
    assert n_crashes == 1, f"{point} never fired"
    # "restart the process": a fresh service over the same root
    svc2 = _client_service(root, FaultPlan(seed=12).fs(), k=depth)
    trials = run_fmin(CLIENT_ALGO, n=N_CHAOS, seed=3, engine=svc2,
                      ask_ahead=depth)
    got = stream(trials)
    assert got == ref, f"resume after {point} diverged"
    tids = [t[0] for t in got]
    assert tids == sorted(set(tids)), "duplicate or lost tids"


def test_resume_from_missing_root_is_refused(tmp_path):
    with pytest.raises(CheckpointError, match="no .* study artifacts"):
        run_fmin(
            CLIENT_ALGO, n=4, engine=True,
            resume_from=str(tmp_path / "nowhere"),
        )


def test_durable_failures_never_rerun_on_resume(tmp_path):
    """A catch=-contained failure is WAL-durable (a ``fail`` record):
    the resumed run restores the STATUS_FAIL doc and does not
    re-evaluate that tid."""
    root = str(tmp_path / "fails")
    calls = []

    def flaky(cfg):
        calls.append(dict(cfg))
        if len(calls) == 5:
            raise ValueError("boom at call 5")
        return objective(cfg)

    t1 = run_fmin(
        CLIENT_ALGO, n=10, seed=3, obj=flaky, catch=(ValueError,),
        engine=True, trials_save_file=root,
    )
    fail_docs = [
        t for t in t1._dynamic_trials
        if t["result"].get("status") == STATUS_FAIL
    ]
    assert len(fail_docs) == 1
    calls_before = len(calls)
    # extend the run from the same root: restored docs (including the
    # failed one) must not be re-evaluated
    t2 = run_fmin(
        CLIENT_ALGO, n=14, seed=0, obj=flaky, catch=(ValueError,),
        engine=True, resume_from=root,
    )
    assert len(calls) == calls_before + 4  # only the 4 new trials ran
    assert stream(t1) == stream(t2)[: len(stream(t1))]
    restored_fail = [
        t for t in t2._dynamic_trials
        if t["result"].get("status") == STATUS_FAIL
    ]
    assert len(restored_fail) == 1
    assert restored_fail[0]["tid"] == fail_docs[0]["tid"]


def test_unified_layout_and_fsck_serve_role(tmp_path):
    """The client root IS a serve study root: one WAL + snapshot
    family under the study name, clean under ``fsck --serve``."""
    from hyperopt_tpu.distributed import fsck

    root = str(tmp_path / "layout")
    run_fmin(CLIENT_ALGO, n=8, seed=3, engine=True,
             trials_save_file=root)
    names = sorted(os.listdir(root))
    assert f"{CLIENT_STUDY}.snap" in names
    assert f"{CLIENT_STUDY}.wal" in names
    rc = fsck.main(["--serve", root])
    assert rc == 0


def test_points_to_evaluate_ride_the_client_path():
    pts = [{"x": 1.0, "lr": 0.1, "q": 4.0, "c": 1}]
    ref = stream(run_fmin(
        partial(tpe_jax.suggest, fused=True, **TPE_KW), n=10,
        points_to_evaluate=pts,
    ))
    got = stream(run_fmin(
        CLIENT_ALGO, n=10, engine=True, points_to_evaluate=pts,
    ))
    assert got == ref
    assert got[0][3]["x"] == [1.0]


# ---------------------------------------------------------------------------
# graftscope: client-path spans
# ---------------------------------------------------------------------------


def test_driver_trial_spans_carry_client_study_id():
    """driver.trial spans on the client path carry the study id, and
    the serve-side ask/tell spans of the SAME recorder carry it too --
    one correlated trace, end to end."""
    from hyperopt_tpu.obs import FlightRecorder

    rec = FlightRecorder(capacity=4096)
    run_fmin(CLIENT_ALGO, n=6, engine=True, recorder=rec)
    spans = rec.tail()
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    assert {"driver.trial", "ask.delivered", "tell"} <= set(by_name)
    for name in ("driver.trial", "ask.delivered", "tell"):
        assert all(
            s.get("study") == CLIENT_STUDY for s in by_name[name]
        ), f"{name} spans lost the client study id"
    # correlation: every driver.trial tid has its ask.delivered twin
    trial_tids = {s["tid"] for s in by_name["driver.trial"]}
    ask_tids = {s["tid"] for s in by_name["ask.delivered"]}
    assert trial_tids <= ask_tids
