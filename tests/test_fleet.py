"""graftfleet unit coverage: the consistent-hash ring's stability
contract, claim/epoch fencing, the drain-deadline backpressure
satellite, ``fsck --serve``, and the TCP router front (ISSUE 13).

The fleet-level chaos scenarios (replica kill, router crash, migration
crash, partition/zombie) live in ``tests/test_fleet_chaos.py``.
"""

import json
import os
import pickle
import socket
import subprocess
import sys
import threading
import time

import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.exceptions import Overloaded, OwnershipLost
from hyperopt_tpu.serve import HashRing, SuggestService
from hyperopt_tpu.serve.fleet import StudyClaim
from hyperopt_tpu.serve.service import _serve_error_reply

SPACE = {
    "x": hp.uniform("x", -5, 5),
    "lr": hp.loguniform("lr", -5, 0),
    "c": hp.choice("c", [0, 1]),
}
ALGO_KW = dict(n_cand=16, n_cand_cat=8)

KEYS = [f"study-{i:04d}" for i in range(2000)]
NODES = [f"r{i}" for i in range(5)]


# ---------------------------------------------------------------------------
# consistent-hash stability (satellite: pinned movement bound +
# cross-process determinism)
# ---------------------------------------------------------------------------


def test_ring_remove_moves_only_the_removed_nodes_keys():
    """The exact stability invariant: removing a replica reassigns the
    keys IT owned and no others -- survivors' keys never move."""
    ring = HashRing(NODES, salt="fp", vnodes=64)
    before = ring.placement(KEYS)
    ring.remove("r2")
    after = ring.placement(KEYS)
    for k in KEYS:
        if before[k] != "r2":
            assert after[k] == before[k], k
        else:
            assert after[k] != "r2"
    moved = sum(1 for k in KEYS if before[k] != after[k])
    assert moved == sum(1 for k in KEYS if before[k] == "r2")
    # ~1/N of the keys belonged to the removed node (pinned bound:
    # within 2x of even share either way)
    assert len(KEYS) / (2 * len(NODES)) <= moved
    assert moved <= 2 * len(KEYS) / len(NODES)


def test_ring_add_moves_bounded_fraction_all_toward_new_node():
    ring = HashRing(NODES, salt="fp", vnodes=64)
    before = ring.placement(KEYS)
    ring.add("r5")
    after = ring.placement(KEYS)
    moved = [k for k in KEYS if before[k] != after[k]]
    assert moved, "a new replica must take some keys"
    assert all(after[k] == "r5" for k in moved)
    # expected share 1/(N+1); pin the 2x bound
    assert len(moved) <= 2 * len(KEYS) / (len(NODES) + 1)


def test_ring_balance_and_salt_sensitivity():
    ring = HashRing(NODES, salt="fp", vnodes=64)
    loads = {n: 0 for n in NODES}
    for k in KEYS:
        loads[ring.owner(k)] += 1
    mean = len(KEYS) / len(NODES)
    assert max(loads.values()) <= 2 * mean
    assert min(loads.values()) >= mean / 3
    # a different guard fingerprint places differently (the salt is
    # load-bearing, not decoration)
    other = HashRing(NODES, salt="other-fp", vnodes=64)
    assert any(
        ring.owner(k) != other.owner(k) for k in KEYS[:200]
    )


def test_ring_placement_deterministic_across_processes():
    """Placement must not depend on PYTHONHASHSEED or process state:
    a subprocess computes the identical map."""
    ring = HashRing(NODES, salt="fp", vnodes=32)
    keys = KEYS[:100]
    here = ring.placement(keys)
    code = (
        "import json, sys\n"
        "from hyperopt_tpu.serve import HashRing\n"
        f"ring = HashRing({NODES!r}, salt='fp', vnodes=32)\n"
        f"print(json.dumps(ring.placement({keys!r})))\n"
    )
    env = dict(os.environ, PYTHONHASHSEED="123",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        env=env, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert json.loads(out.stdout) == here


# ---------------------------------------------------------------------------
# claim/epoch tokens
# ---------------------------------------------------------------------------


def test_claim_acquire_fence_takeover_release(tmp_path):
    root = str(tmp_path)
    c0 = StudyClaim.acquire(root, "s", "r0")
    assert c0.is_live() and c0.epoch == 0
    # a second replica cannot steal without the takeover authority
    with pytest.raises(OwnershipLost):
        StudyClaim.acquire(root, "s", "r1")
    assert c0.is_live()
    # failover takeover bumps the epoch and fences r0 out
    c1 = StudyClaim.acquire(root, "s", "r1", takeover=True)
    assert c1.epoch == c0.epoch + 1
    assert not c0.is_live()
    with pytest.raises(OwnershipLost):
        c0.ensure_live()
    # release is a tombstone (epoch stays monotone), after which an
    # ordinary acquire succeeds without takeover
    c1.release()
    assert not c1.is_live()
    c2 = StudyClaim.acquire(root, "s", "r2")
    assert c2.epoch > c1.epoch
    # releasing a stale claim is a no-op, never a theft
    c0.release()
    assert c2.is_live()


# ---------------------------------------------------------------------------
# satellite: draining refusals carry a concrete retry_after
# ---------------------------------------------------------------------------


def test_draining_overloaded_carries_deadline_retry_after():
    svc = SuggestService(
        SPACE, background=False, max_batch=4, n_startup_jobs=2, **ALGO_KW
    )
    h = svc.create_study("d0", seed=1)
    svc.drain(timeout=9.0, block=False)
    with pytest.raises(Overloaded) as ei:
        h.ask_async()
    e = ei.value
    assert e.reason == "draining"
    # derived from the drain deadline, not the 10 ms queue heuristic
    assert e.retry_after is not None
    assert 1.0 < e.retry_after <= 9.0
    # and it shrinks as the deadline approaches
    time.sleep(0.05)
    with pytest.raises(Overloaded) as ei2:
        h.ask_async()
    assert ei2.value.retry_after < e.retry_after
    # the wire reply forwards the concrete hint
    reply = _serve_error_reply(e)
    assert reply["error_type"] == "Overloaded"
    assert reply["reason"] == "draining"
    assert reply["retry_after"] == e.retry_after
    svc.shutdown()


def test_serve_error_reply_never_ships_null_retry_after():
    reply = _serve_error_reply(Overloaded("bare", reason="draining"))
    assert reply["retry_after"] is not None and reply["retry_after"] > 0


# ---------------------------------------------------------------------------
# satellite: fsck --serve
# ---------------------------------------------------------------------------


def test_fsck_serve_audit_repair_then_restorable(tmp_path):
    """Damage a serve study root with every corruption class a killed
    or failed-over replica can leave; ``fsck --serve --repair`` must
    fix it, and the repaired family must then RESTORE."""
    from hyperopt_tpu.distributed.fsck import audit_serve, repair_serve

    root = str(tmp_path / "root")
    svc = SuggestService(
        SPACE, root=root, owner="r0", background=False, max_batch=4,
        n_startup_jobs=2, snapshot_cadence=2, **ALGO_KW,
    )
    ha = svc.create_study("a", seed=1)
    for tid in range(3):  # snapshot at cadence 2, 1 tell in the WAL
        ha.tell(tid, 0.5 + tid, vals={"x": 0.1, "lr": 0.5, "c": 0})
    hb = svc.create_study("b", seed=2)
    hb.tell(0, 1.5, vals={"x": -0.2, "lr": 0.3, "c": 1})
    # crash semantics: drop the handles, no final snapshots/releases
    for n in ("a", "b"):
        svc.scheduler.study(n).persist.wal.close()

    # damage: torn WAL tail on a, foreign-guard snapshot on b, an
    # orphaned claim token, and a stale snapshot tmp
    with open(os.path.join(root, "a.wal"), "ab") as f:
        f.write(b"\x00garbage torn tail")
    with open(os.path.join(root, "b.snap"), "wb") as f:
        pickle.dump({"guard": ["foreign", 0, "algo", "fp"]}, f)
    with open(os.path.join(root, "zz.claim"), "w") as f:
        f.write(json.dumps({"replica": "gone", "token": "t", "epoch": 3}))
    tmp = os.path.join(root, "a.snap.tmp.999")
    with open(tmp, "w") as f:
        f.write("half")
    os.utime(tmp, (time.time() - 600, time.time() - 600))

    issues = audit_serve(root)
    kinds = {i.kind for i in issues}
    assert kinds == {
        "wal_torn_tail", "ckpt_fingerprint_mismatch", "claim_orphaned",
        "orphaned_snapshot_tmp",
    }, issues
    n = repair_serve(root, issues)
    assert n == len(issues)
    assert audit_serve(root) == []

    # repaired-then-restorable: a new replica adopts both families
    svc2 = SuggestService(
        SPACE, root=root, owner="r1", background=False, max_batch=4,
        n_startup_jobs=2, **ALGO_KW,
    )
    a = svc2.create_study("a", takeover=True)
    b = svc2.create_study("b", takeover=True)
    assert a.n_tells == 3  # 2 from the snapshot + 1 WAL replay
    assert b.n_tells == 1  # quarantined foreign snap, WAL replay won
    svc2.shutdown()


def test_fsck_serve_cli(tmp_path):
    from hyperopt_tpu.distributed import fsck

    root = str(tmp_path / "cli")
    os.makedirs(root)
    with open(os.path.join(root, "x.claim"), "w") as f:
        f.write(json.dumps({"replica": "gone", "token": "t", "epoch": 0}))
    assert fsck.main(["--serve", root]) == 1  # audit-only: found
    assert fsck.main(["--serve", root, "--repair"]) == 0
    assert fsck.main(["--serve", root]) == 0  # clean now


# ---------------------------------------------------------------------------
# the TCP router front
# ---------------------------------------------------------------------------


class _Client:
    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port), timeout=30)
        self.f = self.sock.makefile("rwb")

    def rpc(self, **req):
        self.f.write((json.dumps(req) + "\n").encode())
        self.f.flush()
        return json.loads(self.f.readline())

    def close(self):
        self.f.close()
        self.sock.close()


def _spawn(server):
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return t


def test_tcp_router_routes_and_fails_over(tmp_path):
    """End-to-end over real sockets: two replica backends sharing a
    root, fronted by the TCP router; killing one backend reroutes its
    studies to the survivor, which restores them from the shared
    root."""
    from hyperopt_tpu.serve.fleet import fleet_salt
    from hyperopt_tpu.serve.router import RouterServer, _Backend
    from hyperopt_tpu.serve.service import serve_forever

    root = str(tmp_path / "root")
    svcs, servers = {}, {}
    for rid in ("b0", "b1"):
        svc = SuggestService(
            SPACE, root=root, owner=rid, background=True, max_batch=8,
            n_startup_jobs=2, **ALGO_KW,
        )
        srv = serve_forever(svc, port=0)
        _spawn(srv)
        svcs[rid], servers[rid] = svc, srv
    backends = [
        _Backend(rid, *servers[rid].server_address[:2])
        for rid in ("b0", "b1")
    ]
    router = RouterServer(backends, salt=fleet_salt("tpe", SPACE))
    rsrv = router.serve_forever(port=0)
    _spawn(rsrv)
    host, port = rsrv.server_address[:2]

    cli = _Client(host, port)
    names = [f"t{i}" for i in range(4)]
    try:
        assert cli.rpc(op="ping")["router"] is True
        assert cli.rpc(op="ready")["ready"] is True
        for i, n in enumerate(names):
            assert cli.rpc(op="create_study", name=n, seed=40 + i)["ok"]
        assert cli.rpc(op="studies")["studies"] == sorted(names)
        # both backends must actually hold a share (ring spread)
        shares = {rid: len(svc.studies()) for rid, svc in svcs.items()}
        assert all(v > 0 for v in shares.values()), shares
        served = {}
        for n in names:
            r = cli.rpc(op="ask", study=n, timeout=30)
            assert r["ok"], r
            served[n] = (r["tid"], r["vals"])
            assert cli.rpc(op="tell", study=n, tid=r["tid"],
                           loss=0.25)["ok"]
        # kill b0: graceful service stop, listener closed
        dead = "b0"
        servers[dead].shutdown()
        servers[dead].server_close()
        svcs[dead].shutdown()
        moved = [n for n in names if n in svcs[dead].studies()] or [
            n for n in names
        ]
        # a fresh client connection (fresh backend conns) must be able
        # to serve EVERY study -- the survivor adopts from the root
        cli2 = _Client(host, port)
        for n in names:
            r = cli2.rpc(op="ask", study=n, timeout=30, recover=True)
            assert r["ok"], (n, r)
            assert cli2.rpc(op="tell", study=n, tid=r["tid"],
                            loss=0.5)["ok"]
            b = cli2.rpc(op="best", study=n)
            assert b["ok"] and b["best"] is not None
        assert moved  # the scenario actually exercised failover
        cli2.close()
    finally:
        cli.close()
        for rid in ("b0", "b1"):
            try:
                servers[rid].shutdown()
                servers[rid].server_close()
                svcs[rid].shutdown()
            except Exception:
                pass
        rsrv.shutdown()
        rsrv.server_close()


def test_tcp_router_drain_broadcast(tmp_path):
    """``drain`` on the router front fans out to every live backend:
    one op quiesces the whole fleet, each backend names its own
    (capped) comeback hint and the router reports the slowest."""
    from hyperopt_tpu.serve.fleet import fleet_salt
    from hyperopt_tpu.serve.router import RouterServer, _Backend
    from hyperopt_tpu.serve.service import RETRY_AFTER_CAP, serve_forever

    root = str(tmp_path / "root")
    svcs, servers = {}, {}
    for rid in ("b0", "b1"):
        svc = SuggestService(
            SPACE, root=root, owner=rid, background=True, max_batch=8,
            n_startup_jobs=2, **ALGO_KW,
        )
        srv = serve_forever(svc, port=0)
        _spawn(srv)
        svcs[rid], servers[rid] = svc, srv
    backends = [
        _Backend(rid, *servers[rid].server_address[:2])
        for rid in ("b0", "b1")
    ]
    router = RouterServer(backends, salt=fleet_salt("tpe", SPACE))
    rsrv = router.serve_forever(port=0)
    _spawn(rsrv)
    cli = _Client(*rsrv.server_address[:2])
    try:
        assert cli.rpc(op="create_study", name="d0", seed=1)["ok"]
        r = cli.rpc(op="drain", timeout=5.0)
        assert r["ok"] and r["draining"] is True
        assert r["replicas"] == {"b0": True, "b1": True}
        assert 0 < r["retry_after"] <= RETRY_AFTER_CAP
        # every backend entered draining mode from the ONE router op
        assert all(svc.scheduler.draining for svc in svcs.values())
    finally:
        cli.close()
        for rid in ("b0", "b1"):
            try:
                servers[rid].shutdown()
                servers[rid].server_close()
                svcs[rid].shutdown()
            except Exception:
                pass
        rsrv.shutdown()
        rsrv.server_close()


# ---------------------------------------------------------------------------
# CI/tooling satellite: the static tiers cover the new modules
# ---------------------------------------------------------------------------


def test_fleet_modules_lint_and_trace_clean():
    """graftlint + graftrace over exactly the new fleet/router modules
    (the whole-package gates in test_lint_clean.py cover them too;
    this pins the satellite explicitly, with zero baseline)."""
    from hyperopt_tpu.analysis import lint_paths

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [
        os.path.join(repo, "hyperopt_tpu", "serve", "fleet.py"),
        os.path.join(repo, "hyperopt_tpu", "serve", "router.py"),
    ]
    for pack in ("ast", "trace"):
        result = lint_paths(paths, pack=pack)
        assert not result.findings, (pack, result.findings)


def test_fleet_crash_points_registered():
    from hyperopt_tpu.distributed.faults import (
        ALL_CRASH_POINTS,
        FLEET_CRASH_POINTS,
    )

    assert set(FLEET_CRASH_POINTS) <= set(ALL_CRASH_POINTS)
    assert set(FLEET_CRASH_POINTS) == {
        "fleet_router_after_forward_before_ack",
        "fleet_migrate_after_snapshot_before_handoff",
        "fleet_migrate_after_handoff_before_restore",
        "fleet_claim_tmp_before_rename",
    }
