"""Driver-level chaos: kill ``fmin`` at every armed crash point of the
crash-recovery protocol, resume, and assert the suggestion stream is
BITWISE identical to the uninterrupted same-seed run -- with zero lost
and zero duplicated tells (WAL tell counter == trials count, tids
contiguous).

This is the PR-3 fault-injection discipline extended upward into the
sequential driver (ISSUE 6): the armed points live in
``DRIVER_CRASH_POINTS`` (faults.py), fire inside the write-ahead log
append, the checkpoint publish, the tell-apply, and the ask-ahead
handoff, and every scenario here is deterministic -- fixed seeds,
burst-bounded transient injection, no real sleeps.
"""

import os
import pickle
import time

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp, rand, tpe_jax
from hyperopt_tpu.jax_trials import JaxTrials
from hyperopt_tpu.base import (
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    STATUS_FAIL,
    STATUS_OK,
)
from hyperopt_tpu.distributed import fsck
from hyperopt_tpu.distributed.faults import (
    DRIVER_CRASH_POINTS,
    FaultPlan,
    SimulatedCrash,
)
from hyperopt_tpu.exceptions import CheckpointError
from hyperopt_tpu.fmin import partial
from hyperopt_tpu.utils.checkpoint import DriverRecovery, load_trials

pytestmark = pytest.mark.chaos

SPACE = {"x": hp.uniform("x", -5, 5), "lr": hp.loguniform("lr", -4, 0)}


def quad(cfg):
    return (cfg["x"] - 1) ** 2 + abs(np.log(cfg["lr"]) + 2) / 3


def stream_of(trials):
    return [t["misc"]["vals"] for t in trials.trials]


def run_clean(algo, n, seed=0, trials=None, obj=quad):
    trials = Trials() if trials is None else trials
    fmin(
        obj, SPACE, algo=algo, max_evals=n, trials=trials,
        rstate=np.random.default_rng(seed), show_progressbar=False,
        return_argmin=False,
    )
    return stream_of(trials)


def crash_then_resume(tmp_path, algo, n, point, at, seed=0, cadence=5,
                      tag="", trials_factory=Trials):
    """Kill fmin at the ``at``-th firing of ``point``, then resume with
    a clean fs (the restarted driver) and the ORIGINAL submit seed (the
    bundle-restored rstate supersedes it whenever anything durable
    survived the crash)."""
    path = str(tmp_path / f"ck-{tag}-{point}-{at}.pkl")
    plan = FaultPlan(seed=11).arm(point, at=at)
    rec = DriverRecovery(path, fs=plan.fs(), cadence=cadence)
    with pytest.raises(SimulatedCrash):
        fmin(
            quad, SPACE, algo=algo, max_evals=n,
            trials=trials_factory(), resume_from=rec,
            rstate=np.random.default_rng(seed), show_progressbar=False,
            return_argmin=False,
        )
    assert plan.stats[f"crash:{point}"] == 1, "armed point never fired"
    rec2 = DriverRecovery(path, cadence=cadence)
    fmin(
        quad, SPACE, algo=algo, max_evals=n,
        trials=trials_factory(), resume_from=rec2,
        rstate=np.random.default_rng(seed), show_progressbar=False,
        return_argmin=False,
    )
    final = load_trials(path)
    return final, rec2


def assert_exactly_once(final, rec, n):
    """Zero lost, zero duplicated: n contiguous tids, all DONE, and the
    WAL's monotone tell counter agrees with the trials count."""
    tids = [t["tid"] for t in final.trials]
    assert tids == list(range(n)), "lost or duplicated trial ids"
    assert all(t["state"] == JOB_STATE_DONE for t in final.trials)
    assert rec.wal.total_tells == n, (
        f"WAL logged {rec.wal.total_tells} tells for {n} trials"
    )


# ---------------------------------------------------------------------------
# THE fast-tier acceptance twin: every driver crash point, two depths,
# resumed stream bitwise equal to the uninterrupted run
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("point", DRIVER_CRASH_POINTS)
def test_resume_parity_every_crash_point(tmp_path, point):
    n = 40
    ref = run_clean(rand.suggest, n)
    for at in (1, 4):
        final, rec = crash_then_resume(
            tmp_path, rand.suggest, n, point, at, tag=f"a{at}",
        )
        assert stream_of(final) == ref, (
            f"stream diverged after crash at {point} (hit {at})"
        )
        assert_exactly_once(final, rec, n)


def test_resume_parity_crash_points_deterministic(tmp_path):
    """Same-seed replay of a kill-and-resume scenario produces the
    identical final stream twice (the chaos-suite determinism bar)."""
    n = 30
    streams = []
    for rep in ("r1", "r2"):
        final, _rec = crash_then_resume(
            tmp_path, rand.suggest, n, "after_wal_append_before_tell",
            at=9, tag=rep,
        )
        streams.append(stream_of(final))
    assert streams[0] == streams[1]


def test_resume_parity_fused_resident_tpe(tmp_path):
    """The fused one-dispatch driver (tpe_jax fused=True over a
    device-resident JaxTrials) killed mid-run past a checkpoint
    boundary resumes bitwise -- the resident HistoryState mirror is
    rebuilt from the bundle's obs npz + WAL suffix, and the ask-ahead
    seam position survives."""
    n = 36
    kw = dict(n_EI_candidates=16)
    algo = partial(tpe_jax.suggest, fused=True, **kw)
    ref = run_clean(algo, n, trials=JaxTrials(resident=True))
    final, rec = crash_then_resume(
        tmp_path, algo, n, "after_wal_append_before_tell", at=29,
        cadence=10, tag="fused",
        trials_factory=lambda: JaxTrials(resident=True),
    )
    assert stream_of(final) == ref
    assert_exactly_once(final, rec, n)


@pytest.mark.slow
def test_resume_parity_200_fused_every_point_twice(tmp_path):
    """THE acceptance run (ISSUE 6): 200 fused tpe trials; for every
    driver crash point, kill-and-resume reproduces the uninterrupted
    same-seed 200-trial suggestion stream bitwise, zero lost / zero
    duplicate tells -- and the whole sweep repeats identically under
    the same seed."""
    n = 200
    kw = dict(n_EI_candidates=16)
    algo = partial(tpe_jax.suggest, fused=True, **kw)
    ref = run_clean(algo, n, trials=JaxTrials(resident=True))
    assert len(ref) == n
    # kill depth per point: WAL/tell points fire once or twice per
    # trial (deep hit counts reach mid-run); checkpoint-publish points
    # fire only at the 25-tell cadence
    depth = {
        "before_wal_append": 150,
        "after_wal_append_before_tell": 150,
        "after_tell_before_ask_ahead": 150,
        "after_ckpt_tmp_before_rename": 9,
        "after_ckpt_publish_before_wal_reset": 5,
    }
    for rep in ("r1", "r2"):
        for point in DRIVER_CRASH_POINTS:
            final, rec = crash_then_resume(
                tmp_path, algo, n, point, at=depth[point], cadence=25,
                tag=f"acc-{rep}",
                trials_factory=lambda: JaxTrials(resident=True),
            )
            assert stream_of(final) == ref, (
                f"{rep}: stream diverged after crash at {point}"
            )
            assert_exactly_once(final, rec, n)


def test_driver_survives_transient_fault_storm(tmp_path):
    """No crash points -- a 15% transient errno rate plus 5% torn
    writes on every recovery fs primitive: the retry scaffold absorbs
    it all, the run completes, and the stream still matches the
    fault-free run (twice, same seed)."""
    n = 40
    ref = run_clean(rand.suggest, n)
    for tag in ("s1", "s2"):
        path = str(tmp_path / f"storm-{tag}.pkl")
        plan = FaultPlan(seed=5, rate=0.15, partial_rate=0.05, burst=2)
        rec = DriverRecovery(path, fs=plan.fs(), cadence=5)
        trials = Trials()
        fmin(
            quad, SPACE, algo=rand.suggest, max_evals=n, trials=trials,
            resume_from=rec, rstate=np.random.default_rng(0),
            show_progressbar=False, return_argmin=False,
        )
        assert stream_of(trials) == ref
        assert_exactly_once(load_trials(path), rec, n)
        assert sum(
            v for k, v in plan.stats.items() if k.startswith("error:")
        ) > 0, "the storm never actually injected anything"


# ---------------------------------------------------------------------------
# restore semantics
# ---------------------------------------------------------------------------


def test_restored_rstate_supersedes_passed_rstate(tmp_path):
    path = str(tmp_path / "ck.pkl")
    fmin(
        quad, SPACE, algo=rand.suggest, max_evals=10,
        trials_save_file=path, rstate=np.random.default_rng(0),
        show_progressbar=False, return_argmin=False,
    )
    # resume under a DIFFERENT rstate: the bundle's bit-generator wins
    fmin(
        quad, SPACE, algo=rand.suggest, max_evals=25,
        trials_save_file=path, rstate=np.random.default_rng(999),
        show_progressbar=False, return_argmin=False,
    )
    ref = run_clean(rand.suggest, 25, seed=0)
    assert stream_of(load_trials(path)) == ref


def test_resume_from_missing_checkpoint_refused(tmp_path):
    with pytest.raises(CheckpointError, match="does not exist"):
        fmin(
            quad, SPACE, algo=rand.suggest, max_evals=5,
            resume_from=str(tmp_path / "nope.pkl"),
            rstate=np.random.default_rng(0), show_progressbar=False,
        )


def test_corrupt_checkpoint_raises_clear_error(tmp_path):
    path = str(tmp_path / "ck.pkl")
    fmin(
        quad, SPACE, algo=rand.suggest, max_evals=5,
        trials_save_file=path, rstate=np.random.default_rng(0),
        show_progressbar=False, return_argmin=False,
    )
    with open(path, "wb") as f:
        f.write(b"\x80\x05garbage-truncated")  # torn pickle
    with pytest.raises(CheckpointError) as exc:
        fmin(
            quad, SPACE, algo=rand.suggest, max_evals=10,
            trials_save_file=path, rstate=np.random.default_rng(0),
            show_progressbar=False, return_argmin=False,
        )
    msg = str(exc.value)
    assert path in msg and "fsck" in msg  # names the file + the remedy
    assert f"{path}.meta" in msg  # points at the surviving artifacts


def test_guard_mismatch_refused(tmp_path):
    path = str(tmp_path / "ck.pkl")
    fmin(
        quad, SPACE, algo=rand.suggest, max_evals=5,
        trials_save_file=path, rstate=np.random.default_rng(0),
        show_progressbar=False, return_argmin=False,
    )

    def other_objective(cfg):
        return cfg["x"] ** 2

    with pytest.raises(CheckpointError, match="different study"):
        fmin(
            other_objective, SPACE, algo=rand.suggest, max_evals=10,
            resume_from=path, rstate=np.random.default_rng(0),
            show_progressbar=False, return_argmin=False,
        )


def test_legacy_plain_pickle_still_resumes(tmp_path, caplog):
    """A pre-recovery checkpoint (bare Trials pickle, no meta/WAL)
    loads and continues -- with a warning that the stream cannot match
    the uninterrupted run (the exact silent divergence this PR fixes)."""
    path = str(tmp_path / "legacy.pkl")
    trials = Trials()
    run_clean(rand.suggest, 10, trials=trials)
    with open(path, "wb") as f:
        pickle.dump(trials, f)
    with caplog.at_level("WARNING", logger="hyperopt_tpu.utils.checkpoint"):
        fmin(
            quad, SPACE, algo=rand.suggest, max_evals=20,
            trials_save_file=path, rstate=np.random.default_rng(1),
            show_progressbar=False, return_argmin=False,
        )
    assert len(load_trials(path)) == 20
    assert any(
        "without recovery metadata" in r.message for r in caplog.records
    )


def test_bundle_obs_npz_restores_resident_buffer(tmp_path):
    """The checkpoint bundle carries the dense obs arrays: a resumed
    JaxTrials serves its buffer from the bundle blob (cursor already at
    the bundle's doc count) instead of re-scanning every doc."""
    from hyperopt_tpu.jax_trials import packed_space_for
    from hyperopt_tpu.base import Domain

    path = str(tmp_path / "ck.pkl")
    algo = partial(tpe_jax.suggest, resident=True, n_EI_candidates=16)
    fmin(
        quad, SPACE, algo=algo, max_evals=25,
        trials=JaxTrials(resident=True), trials_save_file=path,
        rstate=np.random.default_rng(3), show_progressbar=False,
        return_argmin=False,
    )
    rec = DriverRecovery(path)
    restored = rec.load()
    trials = restored.trials
    blobs = getattr(trials, "_stashed_obs_npz", [])
    assert blobs, "bundle carried no obs npz"
    space = packed_space_for(Domain(quad, SPACE))
    buf = trials.obs_buffer(space)
    assert not getattr(trials, "_stashed_obs_npz", []), "stash unconsumed"
    assert buf.count == 25
    # bitwise: the restored arrays equal a from-scratch doc-list rebuild
    fresh = JaxTrials(resident=True)
    fresh.insert_trial_docs([dict(t) for t in trials.trials])
    fresh.refresh()
    ref = fresh.obs_buffer(space)
    for a, b in zip(buf.arrays(), ref.arrays()):
        np.testing.assert_array_equal(a[..., :buf.count],
                                      b[..., :ref.count])


# ---------------------------------------------------------------------------
# satellite: non-finite losses are quarantined, not telled as "ok"
# ---------------------------------------------------------------------------


def _nonfinite_objective():
    calls = {"n": 0}

    def obj(cfg):
        calls["n"] += 1
        if calls["n"] % 7 == 3:
            return float("inf")
        if calls["n"] % 7 == 5:
            return float("nan")
        return quad(cfg)

    return obj


@pytest.mark.parametrize("resident", [False, True])
def test_nonfinite_quarantined_on_both_paths(resident):
    """Inf/NaN objective results record as STATUS_FAIL trials and never
    enter the Parzen split -- on the re-upload AND the device-resident
    path -- instead of poisoning best_trial and every later ask."""
    n = 30
    algo = partial(
        tpe_jax.suggest,
        n_EI_candidates=16,
        **({"resident": True} if resident else {}),
    )
    trials = JaxTrials(resident=resident)
    fmin(
        _nonfinite_objective(), SPACE, algo=algo, max_evals=n,
        trials=trials, rstate=np.random.default_rng(2),
        show_progressbar=False, return_argmin=False,
    )
    statuses = [t["result"]["status"] for t in trials.trials]
    n_fail = statuses.count(STATUS_FAIL)
    assert n_fail == len([i for i in range(1, n + 1) if i % 7 in (3, 5)])
    assert all(
        t["result"]["loss"] is None
        for t in trials.trials if t["result"]["status"] == STATUS_FAIL
    )
    assert np.isfinite(trials.best_trial["result"]["loss"])
    # the dense posterior saw only the finite completions
    buf = next(iter(trials._buffers.values()))
    buf.sync(trials)  # ingest the final tell (no ask followed it)
    assert buf.count == n - n_fail
    assert np.all(np.isfinite(buf.losses[: buf.count]))


def test_nonfinite_streams_identical_resident_vs_reupload():
    n = 25
    streams = {}
    for resident in (False, True):
        trials = Trials()
        fmin(
            _nonfinite_objective(), SPACE,
            algo=partial(
                tpe_jax.suggest, n_EI_candidates=16,
                resident=True if resident else None,
            ),
            max_evals=n, trials=trials,
            rstate=np.random.default_rng(4), show_progressbar=False,
            return_argmin=False,
        )
        streams[resident] = stream_of(trials)
    assert streams[False] == streams[True]


def test_nonfinite_dict_result_also_quarantined():
    def obj(cfg):
        return {"status": STATUS_OK, "loss": float("inf")}

    trials = Trials()
    fmin(
        obj, SPACE, algo=rand.suggest, max_evals=3, trials=trials,
        rstate=np.random.default_rng(0), show_progressbar=False,
        return_argmin=False,
    )
    assert all(
        t["result"]["status"] == STATUS_FAIL
        and t["result"]["loss"] is None
        for t in trials.trials
    )


# ---------------------------------------------------------------------------
# satellite: per-trial exception / timeout containment
# ---------------------------------------------------------------------------


def test_catch_records_failed_trial_with_traceback_and_continues():
    calls = {"n": 0}

    def flaky(cfg):
        calls["n"] += 1
        if calls["n"] % 4 == 2:
            raise ValueError("synthetic objective bug")
        return quad(cfg)

    trials = Trials()
    fmin(
        flaky, SPACE, algo=rand.suggest, max_evals=12, trials=trials,
        catch=(ValueError,), rstate=np.random.default_rng(0),
        show_progressbar=False, return_argmin=False,
    )
    assert len(trials) == 12  # the driver continued past every failure
    failed = [
        t for t in trials.trials if t["result"]["status"] == STATUS_FAIL
    ]
    assert len(failed) == 3
    assert all("synthetic objective bug" in t["result"]["failure"]
               for t in failed)
    assert all("ValueError" in t["result"]["traceback"] for t in failed)
    # an uncaught class still aborts: catch is a whitelist, not a net
    with pytest.raises(KeyError):
        fmin(
            lambda cfg: {}["missing"], SPACE, algo=rand.suggest,
            max_evals=3, catch=(ValueError,),
            rstate=np.random.default_rng(0), show_progressbar=False,
        )


def test_trial_timeout_records_fail_and_continues():
    calls = {"n": 0}

    def slow_sometimes(cfg):
        calls["n"] += 1
        if calls["n"] == 2:
            time.sleep(0.4)  # well past the deadline
        return quad(cfg)

    trials = Trials()
    fmin(
        slow_sometimes, SPACE, algo=rand.suggest, max_evals=5,
        trials=trials, trial_timeout=0.05,
        rstate=np.random.default_rng(0), show_progressbar=False,
        return_argmin=False,
    )
    assert len(trials) == 5
    failed = [
        t for t in trials.trials if t["result"]["status"] == STATUS_FAIL
    ]
    assert len(failed) == 1
    assert "trial_timeout" in failed[0]["result"]["failure"]


def test_wal_logged_failure_not_rerun_on_resume(tmp_path):
    """An objective crash (no catch=) aborts fmin AFTER the failure is
    WAL-durable: the resumed run skips the known-bad trial (exactly N
    objective calls across both runs) and its stream matches the
    uninterrupted catch_eval_exceptions run."""
    n = 14
    crash_at = 8

    def make_obj(calls):
        def obj(cfg):
            calls["n"] += 1
            if calls["n"] == crash_at:
                raise RuntimeError("boom")
            return quad(cfg)

        return obj

    # uninterrupted reference: same failure, driver carries on
    ref_calls = {"n": 0}
    ref_trials = Trials()
    fmin(
        make_obj(ref_calls), SPACE, algo=rand.suggest, max_evals=n,
        trials=ref_trials, catch_eval_exceptions=True,
        rstate=np.random.default_rng(0), show_progressbar=False,
        return_argmin=False,
    )
    # crashing run + resume
    path = str(tmp_path / "ck.pkl")
    calls = {"n": 0}
    obj = make_obj(calls)
    with pytest.raises(RuntimeError, match="boom"):
        fmin(
            obj, SPACE, algo=rand.suggest, max_evals=n,
            trials_save_file=path, rstate=np.random.default_rng(0),
            show_progressbar=False, return_argmin=False,
        )
    assert calls["n"] == crash_at
    fmin(
        obj, SPACE, algo=rand.suggest, max_evals=n,
        trials_save_file=path, rstate=np.random.default_rng(0),
        show_progressbar=False, return_argmin=False,
    )
    assert calls["n"] == n  # the errored trial was NOT re-evaluated
    final = load_trials(path)
    assert stream_of(final) == stream_of(ref_trials)
    errored = [
        t for t in final.trials if t["state"] == JOB_STATE_ERROR
    ]
    assert len(errored) == 1
    assert "boom" in errored[0]["misc"]["error"][1]
    assert "RuntimeError" in errored[0]["misc"]["traceback"]


# ---------------------------------------------------------------------------
# satellite: fsck --driver audits + repairs the new corruption classes
# ---------------------------------------------------------------------------


def _driver_family(tmp_path, n=8):
    path = str(tmp_path / "study.pkl")
    fmin(
        quad, SPACE, algo=rand.suggest, max_evals=n,
        trials_save_file=path, rstate=np.random.default_rng(0),
        show_progressbar=False, return_argmin=False,
    )
    return path


def test_fsck_driver_detects_and_repairs(tmp_path, capsys):
    path = _driver_family(tmp_path)
    # torn WAL tail (crash mid-append)
    with open(path + ".wal", "a") as f:
        f.write('deadbeef {"seq": 999, "kind": "tell"')
    # foreign bundle parked under this family's name
    with open(path + ".meta", "wb") as f:
        pickle.dump({"format": 1, "guard": ["foreign-study"],
                     "wal_seq": 0, "rstate": None, "obs_npz": []}, f)
    # orphaned snapshot tmp residue
    old = time.time() - 3600
    tmp = f"{path}.tmp.4242"
    with open(tmp, "w") as f:
        f.write("partial")
    os.utime(tmp, (old, old))

    issues = fsck.audit_driver(path, tmp_grace=60.0)
    assert {i.kind for i in issues} == {
        "wal_torn_tail", "ckpt_fingerprint_mismatch",
        "orphaned_snapshot_tmp",
    }
    assert fsck.main(["--driver", path]) == 1  # audit-only: issues found
    capsys.readouterr()
    assert fsck.main(["--driver", path, "--repair", "--tmp-grace", "60"]) == 0
    capsys.readouterr()
    assert fsck.audit_driver(path, tmp_grace=60.0) == []
    assert not os.path.exists(tmp)
    assert not os.path.exists(path + ".meta")  # quarantined, not deleted
    assert any(".quarantined." in f for f in os.listdir(tmp_path))
    # the repaired family resumes (degraded: no bundle, valid WAL prefix)
    fmin(
        quad, SPACE, algo=rand.suggest, max_evals=12,
        resume_from=path, rstate=np.random.default_rng(0),
        show_progressbar=False, return_argmin=False,
    )
    assert len(load_trials(path)) == 12


def test_fsck_driver_midfile_corruption_quarantines_wal(tmp_path):
    from hyperopt_tpu.utils.wal import TellWAL

    path = _driver_family(tmp_path)
    # repopulate the (checkpoint-compacted) WAL, then corrupt a MIDDLE
    # record -- residue no crash of the protocol itself can produce
    wal_path = path + ".wal"
    wal = TellWAL(wal_path)
    for tid in (100, 101, 102):
        wal.append("tell", {"tid": tid, "state": 2})
    wal.close()
    lines = open(wal_path).read().splitlines(keepends=True)
    assert len(lines) >= 3
    lines[1] = "00000000 " + lines[1].split(" ", 1)[1]
    lines.append("torn-tail-too")
    with open(wal_path, "w") as f:
        f.write("".join(lines))
    issues = fsck.audit_driver(path)
    assert {i.kind for i in issues} == {"wal_corrupt"}
    assert fsck.repair_driver(path, issues) == 1
    assert not os.path.exists(wal_path)  # quarantined aside
    assert fsck.audit_driver(path) == []


def test_fsck_driver_clean_family_is_clean(tmp_path, capsys):
    path = _driver_family(tmp_path)
    assert fsck.audit_driver(path) == []
    assert fsck.main(["--driver", path]) == 0
