"""The on-device optimization loop: one compiled program per experiment
(suggest + evaluate + history append under lax.scan)."""

import numpy as np
import pytest

import jax.numpy as jnp

from hyperopt_tpu import hp
from hyperopt_tpu.device_loop import compile_fmin, fmin_on_device


def quad_space():
    return {
        "x": hp.uniform("x", -5.0, 5.0),
        "y": hp.loguniform("y", np.log(1e-3), np.log(10.0)),
    }


def quad_obj(cfg):
    return (cfg["x"] - 1.0) ** 2 + (jnp.log(cfg["y"]) - jnp.log(0.1)) ** 2


def test_device_loop_tpe_beats_random():
    n = 160
    tpe_runner = compile_fmin(quad_obj, quad_space(), max_evals=n)
    rand_runner = compile_fmin(quad_obj, quad_space(), max_evals=n, algo="rand")
    tpe_bests, rand_bests = [], []
    for seed in (0, 1, 2):
        tpe_out = tpe_runner(seed=seed)
        assert tpe_out["n_evals"] == n
        # history bookkeeping: best really is the min of the losses
        assert tpe_out["best_loss"] == pytest.approx(
            float(tpe_out["losses"].min())
        )
        tpe_bests.append(tpe_out["best_loss"])
        rand_bests.append(rand_runner(seed=seed)["best_loss"])
    # mean over seeds: single-seed ties can happen when the shared
    # random-startup prefix finds the best point
    assert np.mean(tpe_bests) < np.mean(rand_bests)


@pytest.mark.slow
def test_device_loop_sequential_beats_population_at_equal_budget():
    """VERDICT r2 weak #2 regression: at an equal trial budget, sequential
    mode (B=1, one posterior update per trial) must beat wide population
    steps (B=32, budget/32 updates) on the 20-dim mixed space -- the
    round-3 study measured 0.232 vs 0.429 median at 1k trials on chip;
    this pins the ordering at a CI-sized budget."""
    from hyperopt_tpu.models.synthetic import mixed_space, mixed_space_fn_jax

    n = 256
    seq = compile_fmin(
        mixed_space_fn_jax, mixed_space(), max_evals=n, batch_size=1,
        n_EI_candidates=128, n_EI_candidates_cat=24,
    )
    pop = compile_fmin(
        mixed_space_fn_jax, mixed_space(), max_evals=n, batch_size=32,
        n_EI_candidates=128, n_EI_candidates_cat=24,
    )
    seq_bests = [seq(seed=s)["best_loss"] for s in (0, 1, 2)]
    pop_bests = [pop(seed=s)["best_loss"] for s in (0, 1, 2)]
    assert np.mean(seq_bests) < np.mean(pop_bests), (seq_bests, pop_bests)


def test_history_from_trials_warm_starts_device_loop():
    """A host-driven fmin history continues ON-DEVICE: the bridge keeps
    only posterior-eligible trials in tid order, the warm trials count
    toward startup and feed the posterior, and the resumed run improves
    on (or matches) the warm best."""
    from hyperopt_tpu import Trials, fmin, rand
    from hyperopt_tpu.base import JOB_STATE_ERROR
    from hyperopt_tpu.device_loop import history_from_trials

    trials = Trials()
    fmin(
        lambda cfg: (cfg["x"] - 1.0) ** 2
        + (np.log(cfg["y"]) - np.log(0.1)) ** 2,
        quad_space(), algo=rand.suggest, max_evals=40, trials=trials,
        rstate=np.random.default_rng(0), show_progressbar=False,
        return_argmin=False,
    )
    # poison two docs: one errored, one NaN -- neither may enter
    trials._dynamic_trials[3]["state"] = JOB_STATE_ERROR
    trials._dynamic_trials[7]["result"]["loss"] = float("nan")
    trials.refresh()

    hist = history_from_trials(quad_space(), trials)
    assert hist["losses"].shape == (38,)
    assert np.isfinite(hist["losses"]).all()
    host_best = float(hist["losses"].min())

    runner = compile_fmin(
        quad_obj, quad_space(), max_evals=64, batch_size=1,
        warm_capacity=64,
    )
    out = runner(seed=0, init=hist)
    assert out["n_total"] == 38 + 64
    assert out["best_loss"] <= host_best + 1e-6


@pytest.mark.slow
def test_device_loop_hpo_over_lm_training():
    """The whole experiment INCLUDING per-trial model training as one
    XLA program: each trial trains its own TinyLM (lax.fori_loop SGD
    inside the scan step) with the suggested lr/wd; no host round-trips
    until the result."""
    from hyperopt_tpu.models import transformer

    obj = transformer.device_objective(n_steps=3)
    runner = compile_fmin(
        obj, transformer.hpo_space(), max_evals=24, batch_size=4
    )
    out = runner(seed=0)
    assert np.isfinite(out["losses"]).all()
    # lr matters: the best trained member clearly beats the worst
    assert out["best_loss"] < np.max(out["losses"]) - 0.1
    out2 = runner(seed=0)  # compiled program is reusable + deterministic
    np.testing.assert_array_equal(out["losses"], out2["losses"])


def test_device_loop_runner_reuse_and_determinism():
    runner = compile_fmin(quad_obj, quad_space(), max_evals=64, batch_size=8)
    a = runner(seed=3)
    b = runner(seed=3)
    c = runner(seed=4)
    np.testing.assert_array_equal(a["losses"], b["losses"])
    assert not np.array_equal(a["losses"], c["losses"])


def cond_space():
    return {
        "lr": hp.loguniform("lr", np.log(1e-4), np.log(1.0)),
        "arch": hp.choice(
            "arch",
            [
                {"k": 0, "depth": hp.quniform("depth", 2, 8, 1)},
                {"k": 1, "w": hp.uniform("w", 0.0, 1.0)},
            ],
        ),
    }


def cond_obj(cfg, active):
    base = (jnp.log(cfg["lr"]) - jnp.log(3e-3)) ** 2
    arm = jnp.where(
        active["depth"],
        0.1 * (cfg["depth"] - 5.0) ** 2,
        1.0 + (cfg["w"] - 0.5) ** 2,
    )
    return base + arm


@pytest.mark.slow
@pytest.mark.parametrize("algo,joint", [("tpe", False), ("tpe", True),
                                        ("anneal", False)])
def test_device_loop_conditional_space(algo, joint):
    out = fmin_on_device(
        cond_obj, cond_space(), max_evals=96, batch_size=8,
        algo=algo, joint_ei=joint, seed=0,
    )
    # conditional bookkeeping: exactly one branch active per trial
    d = {l: i for i, l in enumerate(["arch", "depth", "lr", "w"])}
    act = out["active"]
    assert act.shape[1] == 96
    assert np.array_equal(act[d["depth"]], out["values"][d["arch"]] == 0)
    assert np.array_equal(act[d["w"]], out["values"][d["arch"]] == 1)
    # best config only contains active labels
    if out["best"]["arch"] == 0:
        assert "depth" in out["best"] and "w" not in out["best"]
    else:
        assert "w" in out["best"] and "depth" not in out["best"]
    # quantized dim stays on grid
    depths = out["values"][d["depth"]][act[d["depth"]]]
    assert np.all(depths == np.round(depths))


@pytest.mark.slow
def test_device_loop_trials_rebuild():
    out = fmin_on_device(
        cond_obj, cond_space(), max_evals=48, batch_size=8, seed=2,
        return_trials=True,
    )
    trials = out["trials"]
    assert len(trials) == 48
    assert min(trials.losses()) == pytest.approx(out["best_loss"])
    best = trials.best_trial
    assert best["result"]["loss"] == pytest.approx(out["best_loss"])
    # docs carry the sparse idxs/vals encoding (conditional dims absent)
    for t in trials.trials:
        vals = t["misc"]["vals"]
        assert (len(vals["depth"]) == 1) != (len(vals["w"]) == 1)


def test_device_loop_nan_losses_masked():
    """Trials whose objective returns NaN are excluded from the posterior
    but the loop still runs to completion."""

    def obj(cfg):
        loss = (cfg["x"] - 1.0) ** 2
        return jnp.where(cfg["x"] < -4.0, jnp.nan, loss)

    out = fmin_on_device(
        obj, {"x": hp.uniform("x", -5.0, 5.0)}, max_evals=80, seed=0
    )
    assert np.isfinite(out["best_loss"])
    assert out["best_loss"] < 1.0


def test_device_loop_rejects_unknown_algo():
    with pytest.raises(ValueError, match="unknown algo"):
        compile_fmin(quad_obj, quad_space(), max_evals=8, algo="random")


def test_device_loop_all_failed_raises():
    from hyperopt_tpu.exceptions import AllTrialsFailed

    runner = compile_fmin(
        lambda cfg: jnp.full_like(cfg["x"], jnp.nan),
        {"x": hp.uniform("x", -1.0, 1.0)},
        max_evals=24,
    )
    with pytest.raises(AllTrialsFailed):
        runner(seed=0)


def test_device_loop_sharded_population():
    """batch axis sharded over an 8-device mesh (GSPMD constraints);
    converges and stays deterministic."""
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8])
    assert devs.size == 8  # conftest forces the 8-device CPU platform
    mesh = Mesh(devs, ("trial",))
    runner = compile_fmin(
        quad_obj, quad_space(), max_evals=256, batch_size=16, mesh=mesh
    )
    a = runner(seed=0)
    b = runner(seed=0)
    np.testing.assert_array_equal(a["losses"], b["losses"])
    assert a["best_loss"] < 0.5

    with pytest.raises(ValueError, match="multiple of mesh axis"):
        compile_fmin(
            quad_obj, quad_space(), max_evals=64, batch_size=3, mesh=mesh
        )


@pytest.mark.slow
def test_device_loop_atpe_beats_plain_tpe():
    """VERDICT r3 weak #5 done-criterion: on-device adaptive TPE
    (``algo='atpe'``: traced stall detection, prior-boost + restart
    fraction, converged-parameter locking, per-family candidate
    adaptation) is at least as good as plain on-device TPE on the
    deceptive trap15 battery AND the 20-dim mixed surrogate, 7-seed
    median (measured at pin time: trap15 0.241 vs 0.249, mixed20 0.367
    vs 0.406; 5 seeds were noise-dominated on trap15, where the host
    study already bounded the stall lever's value at ~2-3%)."""
    from hyperopt_tpu.models.synthetic import (
        _space_trap15, mixed_space, mixed_space_fn_jax,
    )

    def trap15_jax(cfg):
        xs = jnp.stack([cfg[f"t{i}"] for i in range(15)])
        return jnp.mean(jnp.minimum(0.18 + (xs + 2.0) ** 2 / 30.0,
                                    25.0 * (xs - 3.0) ** 2), axis=0)

    for fn, space, evals, cap in [
        (trap15_jax, _space_trap15(), 200, 0.30),
        (mixed_space_fn_jax, mixed_space(), 300, 0.45),
    ]:
        medians = {}
        for algo in ("tpe", "atpe"):
            r = compile_fmin(fn, space, max_evals=evals, batch_size=1,
                             algo=algo)
            medians[algo] = float(np.median(
                [r(seed=s)["best_loss"] for s in range(7)]
            ))
        assert medians["atpe"] <= medians["tpe"] * 1.02, medians
        assert medians["atpe"] < cap, medians


def test_atpe_device_fn_locks_converged_dims():
    """The traced lock set mirrors the host ATPEOptimizer: a dim whose
    elite values have collapsed is frozen to the elite median in
    ~lock_fraction of suggestion columns; the cap (D//2) keeps the less
    converged dim exploring."""
    from hyperopt_tpu.atpe_jax import build_atpe_device_fn
    from hyperopt_tpu.ops.compile import compile_space
    import jax

    ps = compile_space({
        "x": hp.uniform("x", -5.0, 5.0),
        "y": hp.uniform("y", -5.0, 5.0),
    })
    D, cap, n = 2, 64, 40
    rng = np.random.default_rng(0)
    values = np.zeros((D, cap), dtype=np.float32)
    dx = ps.labels.index("x")
    dy = ps.labels.index("y")
    values[dx, :n] = rng.uniform(-5, 5, n)
    values[dy, :n] = rng.uniform(-5, 5, n)
    # improving history (no stall restarts); elites = last 8 trials,
    # whose x collapsed to ~2.0 (std << 0.05 * width) while y stays wide
    losses = np.full(cap, np.inf, dtype=np.float32)
    losses[:n] = 10.0 - 0.2 * np.arange(n)
    values[dx, n - 8: n] = 2.0 + rng.uniform(-0.01, 0.01, 8)
    active = np.zeros((D, cap), dtype=bool)
    active[:, :n] = True
    valid = np.zeros(cap, dtype=bool)
    valid[:n] = True

    fn = build_atpe_device_fn(ps, lf=25.0, lock_fraction=0.5)
    B = 64
    new_vals, new_act = jax.device_get(
        fn(jax.random.key(0), values, active, losses, valid, batch=B)
    )
    elite_x = values[dx, n - 8: n]
    med = 0.5 * (np.sort(elite_x)[3] + np.sort(elite_x)[4])
    locked_cols = np.isclose(new_vals[dx], med, atol=1e-6)
    # ~B * lock_fraction columns frozen to the elite median
    assert 12 <= locked_cols.sum() <= 52, locked_cols.sum()
    # y (cap D//2 = 1) keeps exploring: never frozen to one value
    assert np.unique(np.round(new_vals[dy], 4)).size > B // 2
    assert new_act.all()


@pytest.mark.slow
def test_device_loop_cand_sharded_sequential():
    """The flagship SEQUENTIAL (B=1) mode with the EI candidate sweep
    sharded over the whole 8-device mesh INSIDE the scan (VERDICT r3
    weak #1: population sharding cannot apply at B=1, so this is the
    only way multi-chip accelerates the framework's best-quality mode).
    Deterministic, startup draws identical to the unsharded program
    (shared prior key stream), TPE tail genuinely per-device, quality
    on par."""
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, ("cand",))
    sharded = compile_fmin(
        quad_obj, quad_space(), max_evals=128, batch_size=1,
        mesh=mesh, cand_axis="cand",
    )
    a = sharded(seed=0)
    b = sharded(seed=0)
    np.testing.assert_array_equal(a["losses"], b["losses"])

    plain = compile_fmin(quad_obj, quad_space(), max_evals=128, batch_size=1)
    p = plain(seed=0)
    # identical startup (prior keys are shared), distinct TPE draws
    np.testing.assert_array_equal(a["values"][:, :20], p["values"][:, :20])
    assert not np.array_equal(a["values"][:, 20:], p["values"][:, 20:])
    assert a["best_loss"] < 0.5 and p["best_loss"] < 0.5


@pytest.mark.slow
def test_device_loop_cand_sharded_composes_with_trial_axis():
    """2-D mesh: population over 'trial' AND candidate sweep over 'cand'
    in the same scan step."""
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("trial", "cand"))
    runner = compile_fmin(
        quad_obj, quad_space(), max_evals=128, batch_size=4,
        mesh=mesh, trial_axis="trial", cand_axis="cand",
    )
    a = runner(seed=1)
    b = runner(seed=1)
    np.testing.assert_array_equal(a["losses"], b["losses"])
    assert a["best_loss"] < 0.5


@pytest.mark.slow
def test_device_loop_cand_sharded_conditional_space():
    """Conditional (choice-routed) spaces through the sharded sweep:
    the categorical EI shards too, and activity masks stay consistent."""
    import jax
    from jax.sharding import Mesh

    space = {
        "algo": hp.choice("algo", [
            {"kind": 0, "lr": hp.loguniform("lr", -7.0, 0.0)},
            {"kind": 1, "c": hp.uniform("c", 0.1, 10.0)},
        ]),
    }

    def obj(cfg, active=None):
        lr_loss = (jnp.log(jnp.maximum(cfg["lr"], 1e-8)) + 3.0) ** 2
        c_loss = (cfg["c"] - 2.0) ** 2 + 1.0
        return jnp.where(active["lr"], lr_loss, c_loss)

    mesh = Mesh(np.array(jax.devices()[:8]), ("cand",))
    runner = compile_fmin(
        obj, space, max_evals=96, batch_size=1, mesh=mesh, cand_axis="cand"
    )
    out = runner(seed=0)
    assert out["best_loss"] < 1.0  # found the lr branch optimum
    # activity is one branch per trial
    d = {lab: i for i, lab in enumerate(["algo", "c", "lr"])}
    act = out["active"]
    assert np.array_equal(act[d["lr"]], ~act[d["c"]])


@pytest.mark.slow
def test_device_loop_atpe_cand_sharded():
    """Adaptive TPE with its candidate sweep sharded inside the scan:
    the traced settings/lock layer is device-count-independent, so the
    sharded program stays deterministic and converges."""
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]), ("cand",))
    runner = compile_fmin(
        quad_obj, quad_space(), max_evals=128, batch_size=1,
        algo="atpe", mesh=mesh, cand_axis="cand",
    )
    a = runner(seed=0)
    b = runner(seed=0)
    np.testing.assert_array_equal(a["losses"], b["losses"])
    assert a["best_loss"] < 0.5


def test_device_loop_cand_sharded_with_early_stop():
    """The sharded sweep (shard_map) composes with the while_loop
    early-stop form: a loss_threshold hit stops the cand-sharded
    sequential scan early."""
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]), ("cand",))
    runner = compile_fmin(
        quad_obj, quad_space(), max_evals=256, batch_size=1,
        mesh=mesh, cand_axis="cand", loss_threshold=0.5,
    )
    out = runner(seed=0)
    assert out["best_loss"] <= 0.5
    assert out["n_evals"] < 256  # really stopped early


def test_device_loop_cand_axis_validation():
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]), ("cand",))
    with pytest.raises(ValueError, match="requires a mesh"):
        compile_fmin(quad_obj, quad_space(), max_evals=8, cand_axis="cand")
    with pytest.raises(ValueError, match="not an axis"):
        compile_fmin(quad_obj, quad_space(), max_evals=8,
                     mesh=mesh, cand_axis="nope")
    with pytest.raises(ValueError, match="no candidate sweep"):
        compile_fmin(quad_obj, quad_space(), max_evals=8,
                     mesh=mesh, cand_axis="cand", algo="anneal")
    with pytest.raises(ValueError, match="factorized"):
        compile_fmin(quad_obj, quad_space(), max_evals=8,
                     mesh=mesh, cand_axis="cand", joint_ei=True)
    # a cand-only mesh no longer demands a trial axis at B=1
    runner = compile_fmin(quad_obj, quad_space(), max_evals=8,
                          batch_size=1, mesh=mesh, cand_axis="cand")
    assert callable(runner)
    # ...but at B>1 a NAMED trial axis missing from the mesh still
    # raises (a typo must never silently unshard the population);
    # trial_axis=None is the explicit opt-out
    with pytest.raises(ValueError, match="not an axis"):
        compile_fmin(quad_obj, quad_space(), max_evals=16, batch_size=4,
                     mesh=mesh, trial_axis="trail", cand_axis="cand")
    runner = compile_fmin(quad_obj, quad_space(), max_evals=16,
                          batch_size=4, mesh=mesh, trial_axis=None,
                          cand_axis="cand")
    assert callable(runner)
    with pytest.raises(ValueError, match="nothing to shard"):
        compile_fmin(quad_obj, quad_space(), max_evals=16, batch_size=4,
                     mesh=mesh, trial_axis=None)


def test_device_loop_trials_rebuild_marks_failures():
    from hyperopt_tpu.base import STATUS_FAIL, STATUS_OK

    def obj(cfg):
        return jnp.where(cfg["x"] < 0.0, jnp.nan, cfg["x"] ** 2)

    out = fmin_on_device(
        obj, {"x": hp.uniform("x", -1.0, 1.0)}, max_evals=40, seed=0,
        return_trials=True,
    )
    statuses = out["trials"].statuses()
    assert STATUS_FAIL in statuses and STATUS_OK in statuses
    losses = [l for l in out["trials"].losses() if l is not None]
    assert losses and all(np.isfinite(losses))
    assert min(losses) == pytest.approx(out["best_loss"])


@pytest.mark.slow
def test_device_loop_loss_threshold_stops_early():
    runner = compile_fmin(
        quad_obj, quad_space(), max_evals=512, batch_size=8,
        loss_threshold=0.5,
    )
    out = runner(seed=0)
    assert out["best_loss"] <= 0.5
    assert out["n_evals"] < 512  # stopped before the budget
    assert len(out["losses"]) == out["n_evals"]
    # threshold never reached -> full budget
    runner2 = compile_fmin(
        quad_obj, quad_space(), max_evals=40, batch_size=8,
        loss_threshold=-1.0,
    )
    out2 = runner2(seed=0)
    assert out2["n_evals"] == 40


@pytest.mark.slow
def test_device_loop_no_progress_stops_early():
    """On-device counterpart of early_stop.no_progress_loss: a constant
    objective stops after startup + no_progress_steps batches."""

    def flat(cfg):
        return jnp.ones_like(cfg["x"])

    runner = compile_fmin(
        flat, {"x": hp.uniform("x", -1.0, 1.0)}, max_evals=400,
        batch_size=8, no_progress_steps=3,
    )
    out = runner(seed=0)
    # first batch sets best=1.0; every later batch is stale
    assert out["n_evals"] == 8 * 4, out["n_evals"]
    # an improving objective resets the stale counter: across a few
    # seeds, some run must survive past the flat objective's fixed stop
    # (a broken reset stops EVERY run at exactly startup+3 batches)
    quad_runner = compile_fmin(
        quad_obj, quad_space(), max_evals=400, batch_size=8,
        no_progress_steps=3,
    )
    quad_evals = [quad_runner(seed=s)["n_evals"] for s in (0, 1, 2, 3)]
    assert max(quad_evals) > out["n_evals"], quad_evals

    # all-failed batches must NOT advance the stale counter (parity with
    # early_stop.no_progress_loss: never stop before a best exists)
    def nan_then_quad(cfg):
        return jnp.where(cfg["x"] > 4.0, cfg["x"] ** 2, jnp.nan)

    out3 = compile_fmin(
        nan_then_quad, {"x": hp.uniform("x", -5.0, 5.0)}, max_evals=200,
        batch_size=4, no_progress_steps=2,
    )(seed=0)
    assert np.isfinite(out3["best_loss"])  # survived failed batches

    with pytest.raises(ValueError, match="no_progress_steps"):
        compile_fmin(
            quad_obj, quad_space(), max_evals=8, no_progress_steps=0
        )
    with pytest.raises(ValueError, match="no_progress_steps"):
        compile_fmin(
            quad_obj, quad_space(), max_evals=8, no_progress_steps=2.7
        )


def test_device_loop_warm_start_resume():
    """Checkpoint/resume for the on-device path: a second run seeded with
    the first run's history continues the experiment."""
    runner = compile_fmin(
        quad_obj, quad_space(), max_evals=64, batch_size=8,
        warm_capacity=128,
    )
    first = runner(seed=0)
    assert first["n_total"] == 64
    second = runner(seed=1, init=first)
    assert second["n_evals"] == 64 and second["n_total"] == 128
    # resumed best can only improve on the warm history's best
    assert second["best_loss"] <= first["best_loss"] + 1e-12
    # warm prefix is preserved verbatim in the combined history
    np.testing.assert_array_equal(second["losses"][:64], first["losses"])
    # chains: third leg over the accumulated 128 (within warm_capacity)
    third = runner(seed=2, init=second)
    assert third["n_total"] == 192
    # 192 warm trials exceed warm_capacity=128 -> clear error
    with pytest.raises(ValueError, match="warm_capacity"):
        runner(seed=3, init=third)


def test_device_loop_warm_start_skips_startup():
    """With >= n_startup_jobs warm trials, the resumed run goes straight
    to the TPE model (no random restart): its draws concentrate near the
    warm optimum immediately."""
    space = {"x": hp.uniform("x", -10.0, 10.0)}

    def obj(cfg):
        return (cfg["x"] - 2.0) ** 2

    runner = compile_fmin(
        obj, space, max_evals=96, batch_size=8, warm_capacity=128,
    )
    first = runner(seed=0)
    resumed = runner(seed=1, init=first)
    new_xs = resumed["values"][0, 96:]
    # startup really skipped: the resumed first batch comes from the TPE
    # model, not the prior -- a cold run with the same seed draws its
    # first batch from the prior, so the two must differ
    cold = runner(seed=1)
    assert not np.array_equal(resumed["values"][0, 96:104], cold["values"][0, :8])
    # and the model draws are biased toward the warm optimum vs uniform
    assert np.mean(np.abs(new_xs - 2.0)) < 4.0, new_xs


@pytest.mark.slow
def test_device_loop_warm_start_respects_early_stop_state():
    """Resumed runs inherit the warm best: a warm history already at the
    loss_threshold stops immediately, and no_progress counts against the
    warm best rather than restarting from +inf."""
    runner = compile_fmin(
        quad_obj, quad_space(), max_evals=64, batch_size=8,
        warm_capacity=128, loss_threshold=1e9,  # any finite warm best hits
    )
    first_runner = compile_fmin(
        quad_obj, quad_space(), max_evals=64, batch_size=8, warm_capacity=128,
    )
    first = first_runner(seed=0)
    resumed = runner(seed=1, init=first)
    assert resumed["n_evals"] == 0  # stopped before any new batch
    assert resumed["best_loss"] == pytest.approx(first["best_loss"])

    # no_progress: flat objective can never beat the warm best -> stops
    # after exactly no_progress_steps batches
    def flat(cfg):
        return jnp.ones_like(cfg["x"]) * 1e6

    np_runner = compile_fmin(
        flat, quad_space(), max_evals=400, batch_size=8,
        warm_capacity=128, no_progress_steps=2,
    )
    resumed2 = np_runner(seed=1, init=first)
    assert resumed2["n_evals"] == 16  # 2 stale batches, no inf-reset


def test_device_loop_resume_uses_fresh_stream():
    """A resumed run must not replay the original run's per-step PRNG
    stream, even at the same seed (the warm offset folds into the key)."""
    runner = compile_fmin(
        quad_obj, quad_space(), max_evals=32, batch_size=8, algo="rand",
        warm_capacity=64,
    )
    first = runner(seed=0)
    resumed = runner(seed=0, init=first)
    assert not np.array_equal(first["values"][0], resumed["values"][0, 32:])


@pytest.mark.slow
def test_device_loop_best_is_space_eval_compatible():
    """The best dict uses the same index-form encoding fmin returns, so
    space_eval resolves it to a concrete config."""
    from hyperopt_tpu import space_eval

    out = fmin_on_device(cond_obj, cond_space(), max_evals=48, batch_size=8,
                         seed=0)
    cfg = space_eval(cond_space(), out["best"])
    assert set(cfg) == {"lr", "arch"}
    arm = cfg["arch"]
    assert ("depth" in arm) != ("w" in arm)
    assert arm["k"] in (0, 1)


def test_runner_vectorized_seed_sweep_matches_single_seed():
    """Round-5 seed-sweep vectorization: runner(seed=[...]) returns one
    result per seed, and every per-seed result matches the single-seed
    runner bitwise (the vmapped program advances the same per-seed key
    streams and histories in lockstep)."""
    space = {
        "x": hp.uniform("x", -5.0, 5.0),
        "c": hp.choice("c", [0, 1, 2]),
    }

    def obj(cfg):
        return (cfg["x"] - 1.0) ** 2 + 0.1 * cfg["c"]

    runner = compile_fmin(obj, space, max_evals=48, batch_size=1,
                          n_EI_candidates=16)
    swept = runner(seed=[3, 4, 5])
    assert isinstance(swept, list) and len(swept) == 3
    for seed, out in zip((3, 4, 5), swept):
        single = runner(seed=seed)
        assert out["best_loss"] == single["best_loss"], seed
        assert np.array_equal(out["losses"], single["losses"]), seed
        assert np.array_equal(out["values"], single["values"]), seed
        assert out["best"] == single["best"], seed
    with pytest.raises(ValueError, match="single-seed"):
        runner(seed=[1, 2], init=swept[0])


def test_runner_seed_sweep_composes_with_early_stop():
    """The vmapped while_loop under loss_threshold runs until every
    seed stops; per-seed results still match the single-seed program."""
    space = {"x": hp.uniform("x", -5.0, 5.0)}
    runner = compile_fmin(
        lambda cfg: (cfg["x"] - 1.0) ** 2, space, max_evals=64,
        batch_size=1, n_EI_candidates=8, loss_threshold=0.05,
    )
    swept = runner(seed=[0, 1])
    for seed, out in zip((0, 1), swept):
        single = runner(seed=seed)
        assert out["n_evals"] == single["n_evals"], seed
        assert np.array_equal(out["losses"], single["losses"]), seed
