"""Smoke tests for plotting / graphviz / criteria / profiling (reference:
tests/test_plotting.py etc., SURVEY.md SS4 'non-crash smoke with Agg')."""

import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")
matplotlib.use("Agg")

from hyperopt_tpu import Trials, fmin, hp, rand
from hyperopt_tpu import criteria, graphviz as ht_graphviz, plotting
from hyperopt_tpu.utils.profiling import StepTimer, instrument_algo


@pytest.fixture(scope="module")
def done_trials():
    trials = Trials()
    fmin(
        lambda cfg: (cfg["x"] - 1) ** 2 + cfg["c"] * 0.1,
        {"x": hp.uniform("x", -3, 3), "c": hp.choice("c", [0, 1])},
        algo=rand.suggest,
        max_evals=25,
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    return trials


def test_plot_history_smoke(done_trials):
    fig = plotting.main_plot_history(done_trials, do_show=False)
    assert fig is not None
    matplotlib.pyplot.close("all")


def test_plot_histogram_smoke(done_trials):
    fig = plotting.main_plot_histogram(done_trials, do_show=False)
    assert fig is not None
    matplotlib.pyplot.close("all")


def test_plot_vars_smoke(done_trials):
    fig = plotting.main_plot_vars(done_trials, do_show=False)
    assert fig is not None
    matplotlib.pyplot.close("all")


def test_plot_empty_trials():
    assert plotting.main_plot_histogram(Trials(), do_show=False) is None
    assert plotting.main_plot_vars(Trials(), do_show=False) is None
    matplotlib.pyplot.close("all")


def test_graphviz_dot_output():
    space = hp.choice(
        "c", [{"x": hp.uniform("x", 0, 1)}, {"y": hp.lognormal("y", 0, 1)}]
    )
    dot = ht_graphviz.dot_hyperparameters(space)
    assert dot.startswith("digraph")
    assert dot.rstrip().endswith("}")
    for label in ("c", "x", "y", "switch"):
        assert label in dot
    assert dot.count("->") > 5


# -- criteria ---------------------------------------------------------------


def test_ei_gaussian_against_empirical():
    rng = np.random.default_rng(0)
    mean, var, thresh = 1.0, 4.0, 2.0
    samples = rng.normal(mean, np.sqrt(var), size=200_000)
    analytic = criteria.EI_gaussian(mean, var, thresh)
    empirical = criteria.EI_empirical(samples, thresh)
    assert analytic == pytest.approx(empirical, rel=0.02)


def test_logei_matches_log_of_ei_in_bulk():
    mean, var = 0.0, 1.0
    for thresh in (-1.0, 0.0, 1.0, 3.0):
        assert criteria.logEI_gaussian(mean, var, thresh) == pytest.approx(
            np.log(criteria.EI_gaussian(mean, var, thresh)), abs=1e-6
        )


def test_logei_finite_deep_in_tail():
    val = criteria.logEI_gaussian(0.0, 1.0, 40.0)
    assert np.isfinite(val)
    assert val < -700  # naive log(EI) would be -inf here


def test_ucb():
    assert criteria.UCB(1.0, 4.0, 2.0) == pytest.approx(5.0)


# -- profiling --------------------------------------------------------------


def test_step_timer_and_instrumented_algo():
    timer = StepTimer()
    timed = instrument_algo(rand.suggest, timer)
    trials = Trials()
    fmin(
        lambda x: x**2, hp.uniform("x", -1, 1), algo=timed, max_evals=5,
        trials=trials, rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    s = timer.summary()["suggest"]
    assert s["count"] == 5
    assert s["total_s"] >= 5 * s["min_s"]
    timer.log_summary()


def test_enable_compilation_cache(tmp_path):
    import os

    import jax

    from hyperopt_tpu.utils import enable_compilation_cache

    prev = (
        jax.config.jax_compilation_cache_dir,
        jax.config.jax_persistent_cache_min_entry_size_bytes,
        jax.config.jax_persistent_cache_min_compile_time_secs,
    )
    try:
        if jax.default_backend() == "cpu":
            # the CPU backend refuses by default: jaxlib 0.4.36's
            # warm-cache executable deserializer corrupts the heap
            # (FAILURES.md "Known test debt")
            assert enable_compilation_cache(str(tmp_path / "xla")) is None
            assert jax.config.jax_compilation_cache_dir == prev[0]
        d = enable_compilation_cache(str(tmp_path / "xla"), force_cpu=True)
        assert d == str(tmp_path / "xla")
        assert os.path.isdir(d)
        assert jax.config.jax_compilation_cache_dir == d
        # a compile flows through the now-enabled cache
        jax.jit(lambda x: x * 2 + 1)(jax.numpy.arange(8)).block_until_ready()
    finally:  # process-global config: restore for later tests
        jax.config.update("jax_compilation_cache_dir", prev[0])
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", prev[1])
        jax.config.update("jax_persistent_cache_min_compile_time_secs", prev[2])
