"""The examples are user-facing contract surface: the quick ones must run
to completion as real subprocesses on the hermetic CPU platform."""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(name, extra_env=None, timeout=600):
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, os.path.join(_ROOT, "examples", name)],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=_ROOT,
    )


def test_example_quickstart():
    out = run_example("01_quickstart.py")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "best loss:" in out.stdout


def test_example_conditional_space():
    out = run_example("02_conditional_space.py")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "best vals" in out.stdout


def test_example_sharded_suggest_virtual_mesh():
    out = run_example(
        "06_sharded_suggest.py", {"HYPEROPT_TPU_VIRTUAL_MESH": "1"}
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "best loss:" in out.stdout
    # the example prints the devices it actually ran on; a pre-latched
    # platform plugin (this container's tunnel sitecustomize) may
    # legitimately override the virtual-mesh env vars, so only the
    # mesh-agnostic contract is asserted here -- the 8-device sharded
    # program itself is covered by tests/test_sharding.py
    assert "devices:" in out.stdout


@pytest.mark.slow
def test_example_device_loop():
    out = run_example("03_device_loop.py", timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "trials/s" in out.stdout


def test_example_speculative_sequential():
    out = run_example("07_speculative_sequential.py")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "speculative=8" in out.stdout and "done" in out.stdout
