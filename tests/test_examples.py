"""The examples are user-facing contract surface: the quick ones must run
to completion as real subprocesses on the hermetic CPU platform."""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.examples

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(name, extra_env=None, timeout=600, args=()):
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, os.path.join(_ROOT, "examples", name), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=_ROOT,
    )


def test_every_example_has_a_test():
    """Examples are user-facing contract surface (VERDICT r3 weak #6): a
    rotted example is a broken quickstart, so EVERY file in examples/
    must be executed by some test in this module."""
    covered = {
        "01_quickstart.py", "02_conditional_space.py", "03_device_loop.py",
        "04_distributed_workers.py", "05_population_training.py",
        "06_sharded_suggest.py", "07_speculative_sequential.py",
        "08_hpo_over_training.py", "09_pbt_and_sha.py", "roofline.py",
        "scheduler_battery.py", "soak_10k.py", "study_device_loop_batch.py",
    }
    on_disk = {
        f for f in os.listdir(os.path.join(_ROOT, "examples"))
        if f.endswith(".py")
    }
    assert on_disk == covered, (
        f"examples/ changed without test coverage: "
        f"missing tests for {sorted(on_disk - covered)}, "
        f"stale entries {sorted(covered - on_disk)}"
    )


@pytest.mark.slow
def test_example_quickstart():
    out = run_example("01_quickstart.py")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "best loss:" in out.stdout


@pytest.mark.slow
def test_example_conditional_space():
    out = run_example("02_conditional_space.py")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "best vals" in out.stdout


@pytest.mark.slow
def test_example_sharded_suggest_virtual_mesh():
    out = run_example(
        "06_sharded_suggest.py", {"HYPEROPT_TPU_VIRTUAL_MESH": "1"}
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "best loss:" in out.stdout
    # the example prints the devices it actually ran on; a pre-latched
    # platform plugin (this container's tunnel sitecustomize) may
    # legitimately override the virtual-mesh env vars, so only the
    # mesh-agnostic contract is asserted here -- the 8-device sharded
    # program itself is covered by tests/test_sharding.py
    assert "devices:" in out.stdout


@pytest.mark.slow
def test_example_device_loop():
    out = run_example("03_device_loop.py", timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "trials/s" in out.stdout


@pytest.mark.slow
def test_example_speculative_sequential():
    out = run_example("07_speculative_sequential.py")
    assert out.returncode == 0, out.stderr[-2000:]
    assert "speculative=8" in out.stdout and "done" in out.stdout


@pytest.mark.slow
def test_example_distributed_workers():
    """Driver + two real worker subprocesses over the filequeue, then
    ASHA over the SAME workers (the re-published budget-aware Domain
    is picked up by the live worker pool)."""
    out = run_example("04_distributed_workers.py", timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "best loss:" in out.stdout
    assert "asha rungs:" in out.stdout
    assert "asha best loss:" in out.stdout


@pytest.mark.slow
def test_example_population_training():
    out = run_example("05_population_training.py", timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "best loss" in out.stdout
    assert "gen 5" in out.stdout


@pytest.mark.slow
def test_example_hpo_over_training_smoke():
    out = run_example(
        "08_hpo_over_training.py", timeout=900,
        args=("--evals", "64", "--steps", "2"),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "best next-token loss" in out.stdout


@pytest.mark.slow
def test_example_pbt_and_sha_smoke():
    out = run_example(
        "09_pbt_and_sha.py", timeout=900, args=("--pop", "4", "--rounds", "2")
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "PBT:" in out.stdout and "SHA: rungs" in out.stdout
    assert "PBT resumed" in out.stdout and "Hyperband: brackets" in out.stdout


@pytest.mark.slow
def test_example_roofline_smoke():
    out = run_example(
        "roofline.py", timeout=900,
        args=("--batch", "64", "--n-calls", "3"),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert '"pct_of_vpu_peak_low"' in out.stdout


@pytest.mark.slow
def test_example_soak_smoke():
    out = run_example(
        "soak_10k.py", timeout=900,
        args=("--max-obs", "500", "--batch", "64", "--n-calls", "2"),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert '"suggest_per_sec_B1024"' in out.stdout


@pytest.mark.slow
def test_example_study_device_loop_batch_smoke():
    out = run_example(
        "study_device_loop_batch.py", timeout=900,
        args=("--evals", "64", "--seeds", "1", "--batches", "1", "8"),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert '"posterior_updates"' in out.stdout


@pytest.mark.slow
def test_example_scheduler_battery_smoke():
    """The --quick tier of the scheduler quality battery (round 5): the
    deterministic drivers run at near-equal spend (within 20% of T) on
    the surrogate domain, ASHA's reported spend stays inside its
    measured sanity envelope, and every cell reports a finite
    true-best."""
    import json
    import math

    out = run_example("scheduler_battery.py", args=("--quick",),
                      extra_env={"HYPEROPT_TPU_COMPILATION_CACHE": "0"})
    assert out.returncode == 0, out.stderr[-2000:]
    last = json.loads(out.stdout.strip().splitlines()[-1])
    cells = last["battery"]
    assert set(cells) == {
        f"surrogate/{s}" for s in
        ("tpe_fmin", "sha", "hyperband", "bohb", "asha_4w", "asha_8w")
    }
    for name, cell in cells.items():
        assert math.isfinite(cell["median_true_best"])
        if "asha" in name:
            # ASHA's spend is REPORTED, not pre-accounted (async
            # promotion is thread-timing-dependent): 24 measured runs
            # span 396-684 on this container, so the smoke bound is a
            # sanity envelope, not an equal-spend claim
            assert 345 <= cell["median_spend"] <= 850, (name, cell)
        else:
            # deterministic drivers: equal-budget within 20% of T=432
            assert 345 <= cell["median_spend"] <= 520, (name, cell)
