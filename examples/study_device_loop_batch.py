"""Quality-vs-batch study for the on-device experiment loop.

VERDICT r2 weak #2: at equal trial budget the flagship on-device path
(``device_loop.compile_fmin``, B=32) traded 2.5x worse best-loss than the
host-driven sequential loop (0.55 vs 0.22 at ~1k trials) because B-wide
population steps mean only ``max_evals / B`` posterior updates.  This
study measures best-loss and on-device wall-clock across population
sizes B in {1, 8, 32, 128} x seeds on the 20-dim mixed space, with the
per-family candidate defaults matched to the host path (cont 128 /
cat 24 -- the round-2 measured default).

Run on the real TPU::

    python examples/study_device_loop_batch.py [--evals 1024] [--seeds 5]

Prints one JSON line per batch size plus a summary table.
"""

import argparse
import os
import json
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--evals", type=int, default=1024)
    ap.add_argument("--seeds", type=int, default=5)
    ap.add_argument("--batches", type=int, nargs="+", default=[1, 8, 32, 128])
    ap.add_argument("--n-cand", type=int, default=128)
    ap.add_argument("--n-cand-cat", type=int, default=24)
    args = ap.parse_args()
    if os.environ.get("HYPEROPT_TPU_COMPILATION_CACHE", "1") != "0":
        from hyperopt_tpu.utils import enable_compilation_cache

        enable_compilation_cache()

    import jax

    from hyperopt_tpu.device_loop import compile_fmin
    from hyperopt_tpu.models.synthetic import mixed_space, mixed_space_fn_jax

    print(f"platform: {jax.devices()[0].platform}")
    rows = []
    for B in args.batches:
        runner = compile_fmin(
            mixed_space_fn_jax,
            mixed_space(),
            max_evals=args.evals,
            batch_size=B,
            n_EI_candidates=args.n_cand,
            n_EI_candidates_cat=args.n_cand_cat,
        )
        t0 = time.perf_counter()
        runner(seed=99)  # compile
        compile_s = time.perf_counter() - t0
        bests, times = [], []
        for seed in range(args.seeds):
            t0 = time.perf_counter()
            out = runner(seed=seed)
            times.append(time.perf_counter() - t0)
            bests.append(out["best_loss"])
        row = {
            "batch_size": B,
            "compile_seconds": round(compile_s, 2),
            "median_best": round(float(np.median(bests)), 4),
            "best_per_seed": [round(b, 4) for b in bests],
            "median_seconds": round(float(np.median(times)), 3),
            "n_evals": int(out["n_evals"]),
            "posterior_updates": int(out["n_evals"]) // B,
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    print("\nB     median_best  median_s  updates")
    for r in rows:
        print(
            f"{r['batch_size']:<6}{r['median_best']:<13}"
            f"{r['median_seconds']:<10}{r['posterior_updates']}"
        )


if __name__ == "__main__":
    main()
