"""Quickstart: minimize a function over a mixed search space.

Identical shape to the reference hyperopt workflow -- only the algo
module changes (tpe_jax = the TPU path; tpe = the host parity path).

    python examples/01_quickstart.py
"""

import numpy as np

from hyperopt_tpu import STATUS_OK, Trials, fmin, hp, space_eval, tpe_jax


def objective(cfg):
    loss = (cfg["x"] - 0.7) ** 2 + abs(cfg["n_layers"] - 3) * 0.1
    if cfg["activation"] == "relu":
        loss += 0.05
    # dict-return form with status, like the reference
    return {"loss": loss, "status": STATUS_OK}


space = {
    "x": hp.uniform("x", -5.0, 5.0),
    "n_layers": hp.quniform("n_layers", 1, 8, 1),
    "activation": hp.choice("activation", ["relu", "gelu", "tanh"]),
    "lr": hp.loguniform("lr", np.log(1e-5), np.log(1e-1)),
}


def main():
    trials = Trials()
    best = fmin(
        objective,
        space,
        algo=tpe_jax.suggest,
        max_evals=100,
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    print("argmin (index form):", best)
    print("argmin (config form):", space_eval(space, best))
    print("best loss:", trials.best_trial["result"]["loss"])


if __name__ == "__main__":
    main()
