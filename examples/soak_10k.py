"""10k-observation soak of the observation path (VERDICT r2 item 8).

The round-2 soak measured the bucketed-upload optimization at 2,500
observations; this drives the SAME real ingestion path (completed trial
docs -> ``Trials`` store -> ``ObsBuffer.sync`` -> pow2-bucketed device
upload) to 10,000+ observations, recording at each checkpoint:

  * capacity-bucket growth (128 -> 16384 by 4x capacity, pow2 upload),
  * batched suggest throughput (B=1024) against the live bucket,
  * host-mirror memory (buffer nbytes + process RSS delta).

Run on the real TPU::

    python examples/soak_10k.py [--max-obs 10000]

Prints one JSON line per checkpoint plus a summary table.
"""

import argparse
import os
import json
import resource
import time

import numpy as np


def rss_mb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-obs", type=int, default=10_000)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--n-cand", type=int, default=128)
    ap.add_argument("--n-calls", type=int, default=8)
    ap.add_argument("--above-cap", type=int, default=None,
                    help="above-model compaction cap (default: framework "
                    "default; 0 = full-width scoring, the pre-round-6 "
                    "behavior this soak originally measured)")
    args = ap.parse_args()
    if os.environ.get("HYPEROPT_TPU_COMPILATION_CACHE", "1") != "0":
        from hyperopt_tpu.utils import enable_compilation_cache

        enable_compilation_cache()

    import jax

    from hyperopt_tpu import rand, tpe_jax
    from hyperopt_tpu.base import Domain, JOB_STATE_DONE
    from hyperopt_tpu.jax_trials import JaxTrials, obs_buffer_for
    from hyperopt_tpu.models.synthetic import mixed_space, mixed_space_fn

    platform = jax.devices()[0].platform
    print(f"platform: {platform}")
    domain = Domain(mixed_space_fn, mixed_space())
    trials = JaxTrials()
    rng = np.random.default_rng(0)
    rss0 = rss_mb()

    checkpoints = [500, 1000, 2500, 5000, 10_000]
    checkpoints = [c for c in checkpoints if c <= args.max_obs]
    fn_cache = {}
    rows = []
    n_have = 0
    for target in checkpoints:
        # ingest through the REAL doc path (suggest -> complete -> sync)
        while n_have < target:
            chunk = min(500, target - n_have)
            ids = trials.new_trial_ids(chunk)
            docs = rand.suggest(ids, domain, trials, seed=n_have)
            for doc in docs:
                doc["state"] = JOB_STATE_DONE
                doc["result"] = {
                    "status": "ok", "loss": float(rng.uniform(0, 10))
                }
            trials.insert_trial_docs(docs)
            trials.refresh()
            n_have += chunk
        buf = obs_buffer_for(domain, trials)
        assert buf.count == target, (buf.count, target)
        # with compaction active the bucket schedule coarsens past the
        # cap (fewer recompiles -- the round-6 'stop re-bucketing' rule)
        a_cap = tpe_jax._resolve_above_cap(args.above_cap)
        bucket = buf._device_bucket(pow2_cap=a_cap)
        arrays = buf.device_arrays(pow2_cap=a_cap)

        fn = fn_cache.get(bucket)
        if fn is None:
            fn = fn_cache[bucket] = tpe_jax.build_suggest_fn(
                buf.space, args.n_cand, 0.25, 25.0, 1.0, n_cand_cat=24,
                above_cap=args.above_cap,
            )
        key = jax.random.key(target)
        out = fn(key, *arrays, batch=args.batch)
        _ = np.asarray(out[0][:1, :1])  # compile + force
        keys = list(jax.random.split(key, args.n_calls))
        _ = np.asarray(jax.random.key_data(keys[-1]))
        t0 = time.perf_counter()
        for i in range(args.n_calls):
            out = fn(keys[i], *arrays, batch=args.batch)
        _ = np.asarray(out[0][:1, :1])  # fetch forces completion
        dt = time.perf_counter() - t0
        sugg_rate = args.batch * args.n_calls / dt

        buf_mb = sum(a.nbytes for a in buf.arrays()) / 1e6
        row = {
            "n_obs": target,
            "capacity": buf.capacity,
            "device_bucket": bucket,
            "above_cap": 0 if a_cap is None else a_cap,
            "suggest_per_sec_B1024": round(sugg_rate, 1),
            "buffer_mb": round(buf_mb, 2),
            "rss_delta_mb": round(rss_mb() - rss0, 1),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    print("\nn_obs  bucket  sugg/s   buf_MB  rss_dMB")
    for r in rows:
        print(
            f"{r['n_obs']:<7}{r['device_bucket']:<8}"
            f"{r['suggest_per_sec_B1024']:<9}{r['buffer_mb']:<8}"
            f"{r['rss_delta_mb']}"
        )


if __name__ == "__main__":
    main()
