"""The whole experiment on-device: thousands of trials per second.

For JAX-traceable objectives, device_loop.compile_fmin compiles suggest
+ evaluate + history append into ONE XLA program (no host round trips).
Reuse the runner across seeds to amortize compilation.

    python examples/03_device_loop.py
"""

import os

import time

import jax.numpy as jnp
import numpy as np

from hyperopt_tpu import hp
from hyperopt_tpu.device_loop import compile_fmin

space = {
    "x": hp.uniform("x", -5.0, 5.0),
    "lr": hp.loguniform("lr", np.log(1e-4), np.log(1.0)),
    "arch": hp.choice(
        "arch",
        [
            {"kind": 0, "depth": hp.quniform("depth", 2, 8, 1)},
            {"kind": 1, "width": hp.uniform("width", 0.0, 1.0)},
        ],
    ),
}


def objective(cfg, active):
    """Receives [batch] arrays (+ per-dim active masks for conditionals)."""
    base = (cfg["x"] - 1.0) ** 2 + (jnp.log(cfg["lr"]) - jnp.log(3e-3)) ** 2
    arm = jnp.where(
        active["depth"],
        0.1 * (cfg["depth"] - 5.0) ** 2,
        0.5 + (cfg["width"] - 0.5) ** 2,
    )
    return base + arm


def main():
    if os.environ.get("HYPEROPT_TPU_COMPILATION_CACHE", "1") != "0":
        from hyperopt_tpu.utils import enable_compilation_cache

        enable_compilation_cache()
    runner = compile_fmin(
        objective, space, max_evals=4096, batch_size=64,
        n_EI_candidates=64,
    )
    out = runner(seed=0)  # includes compile
    t0 = time.perf_counter()
    out = runner(seed=1)
    dt = time.perf_counter() - t0
    print(f"4096 trials in {dt*1e3:.0f} ms  ({4096/dt:,.0f} trials/s)")
    print("best:", out["best"], "loss:", round(out["best_loss"], 5))

    # seed sweep, compilation amortized
    for seed in range(2, 5):
        print(f"seed {seed}: best {runner(seed=seed)['best_loss']:.5f}")


if __name__ == "__main__":
    main()
