"""Population-based hyperparameter search for a ResNet (config #4).

A population of network replicas trains simultaneously -- `vmap` over
population members, optionally sharded over a device mesh -- while TPE
suggests each generation's (lr, weight-decay) from the previous
generations' losses. The suggest step and every member's train steps are
compiled XLA programs; the driver loop only moves a handful of scalars.

    python examples/05_population_training.py
"""

import numpy as np

from hyperopt_tpu import Trials, fmin, tpe_jax
from hyperopt_tpu.models import resnet


def main():
    pop = 4          # members per generation
    generations = 6
    # factory returns an fmin-compatible objective: 3 SGD steps of a tiny
    # ResNet member at the suggested (lr, wd), loss = final train CE
    objective = resnet.population_objective(n_steps=3, batch_size=32)

    trials = Trials()
    fmin(
        objective,
        resnet.hpo_space(),
        algo=tpe_jax.suggest,
        max_evals=pop * generations,
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
        max_queue_len=pop,  # one TPE program suggests the whole generation
    )
    best = trials.best_trial
    lr = best["misc"]["vals"]["lr"][0]
    wd = best["misc"]["vals"]["wd"][0]
    print(f"best loss {best['result']['loss']:.4f} at lr={lr:.5f} wd={wd:.6f}")
    print("losses by generation:")
    losses = trials.losses()
    for g in range(generations):
        gen = losses[g * pop:(g + 1) * pop]
        print(f"  gen {g}: best {min(gen):.4f}")


if __name__ == "__main__":
    main()
