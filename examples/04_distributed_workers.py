"""Distributed trial farming over a shared filesystem.

The Mongo-worker role of the reference (SURVEY.md SS3.4) on the queue
substrate TPU pods actually share: a directory. The driver enqueues NEW
trials; workers reserve them with an atomic rename (CAS), evaluate, and
write results back. Dead workers' reservations are reaped after
--reserve-timeout.

Run the driver:
    python examples/04_distributed_workers.py /tmp/exp1
Run N workers (any hosts mounting the same path):
    hyperopt-tpu-worker --dir /tmp/exp1

(This example also works standalone: with no workers attached it spawns
two local worker subprocesses.)
"""

import subprocess
import sys
import tempfile

import numpy as np

from hyperopt_tpu import fmin, tpe_jax
from hyperopt_tpu.distributed import FileTrials

# NOTE: like the reference's Mongo workers, the objective ships to the
# workers by pickle, so it must live in an importable module -- a
# __main__-level function would fail to unpickle on the worker side.
from hyperopt_tpu.models.synthetic import branin_fn, DOMAINS

space = DOMAINS["branin"].make_space()
objective = branin_fn


def main():
    exp_dir = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp()
    print("experiment dir:", exp_dir)

    trials = FileTrials(exp_dir)
    workers = [
        subprocess.Popen(
            [sys.executable, "-m", "hyperopt_tpu.distributed.worker",
             "--dir", exp_dir, "--poll-interval", "0.05",
             "--last-job-timeout", "60"],
        )
        for _ in range(2)
    ]
    try:
        best = fmin(
            objective, space, algo=tpe_jax.suggest, max_evals=40,
            trials=trials, rstate=np.random.default_rng(0),
            show_progressbar=False, max_queue_len=4,
        )
        print("best:", best)
        print("best loss:", min(trials.losses()))

        # The async scheduler over the SAME worker pool: ASHA promotion
        # decisions on the driver, budget-aware evaluations farmed
        # through the queue (each job doc names its own Domain
        # attachment, so the fmin run's Domain above stays untouched
        # and the live workers resolve the right objective per job).
        from hyperopt_tpu.distributed import asha_filequeue
        from hyperopt_tpu.models.synthetic import (
            budgeted_quadratic_fn, budgeted_quadratic_space,
        )

        out = asha_filequeue(
            budgeted_quadratic_fn, budgeted_quadratic_space(),
            max_budget=9, dirpath=exp_dir, eta=3, max_jobs=30,
            inflight=4, rstate=np.random.default_rng(0),
            eval_timeout=300.0,
        )
        print("asha rungs:", [(r["budget"], r["n"]) for r in out["rungs"]])
        print("asha best loss:", out["best_loss"])
    finally:
        for w in workers:
            w.terminate()


if __name__ == "__main__":
    main()
