"""Scheduler-vs-scheduler quality battery (VERDICT r4 weak #5).

When does a budget-aware scheduler earn its complexity?  SHA, Hyperband,
ASHA (4 and 8 workers), BOHB (``budget_aware(tpe_jax.suggest)`` rung-0
model fitting), and plain full-fidelity TPE ``fmin`` run at EQUAL total
budget on the repo's own battery domains (surrogate-8dim, trap15,
NAS-Bench), >= 5 seeds, and report the TRUE loss of the configuration
each scheduler returns.

Multi-fidelity protocol (the standard synthetic setup): the budgeted
objective is ``f(cfg) + noise(cfg) / budget`` with deterministic
per-config noise, so cheap rungs are informative but unreliable and the
max-budget evaluation is nearly exact.  Total budget T = 432 units
(= 16 full-fidelity evaluations at max_budget 27):

* TPE ``fmin``: 16 evaluations at budget 27 (full fidelity).
* SHA: 4 successive-halving brackets of 27 configs (4 x 108 = 432).
* Hyperband / BOHB: one full spread, s_max = 3 (423 units).
* ASHA: ``max_jobs`` chosen to land near T; the ACTUAL spend is
  reported next to the result (async promotion makes exact
  pre-accounting impossible -- honesty over symmetry).

Quality metric: ``f(best_config)`` -- the noise-free loss of the
incumbent each scheduler would hand the user.

    python examples/scheduler_battery.py [--seeds 5] [--domains surrogate,trap15,nasbench]
    python examples/scheduler_battery.py --quick   # CI smoke
"""

import argparse
import json
import os

import numpy as np

MAX_BUDGET = 27
ETA = 3
TOTAL = 432
NOISE_SIGMA = 0.3


def _domains():
    from hyperopt_tpu.models import nasbench, surrogate
    from hyperopt_tpu.models.synthetic import battery

    trap = battery(names=["trap15"])[0]
    return {
        "surrogate": (surrogate.objective, surrogate.space),
        "trap15": (trap.fn, trap.make_space),
        "nasbench": (nasbench.objective, nasbench.space),
    }


def _noise(cfg):
    """Deterministic per-config pseudo-noise in N(0, 1) (thread-safe:
    derived from the config alone, no shared RNG)."""
    key = hash(repr(sorted((k, round(v, 9) if isinstance(v, float) else v)
                           for k, v in cfg.items())))
    return float(np.random.default_rng(abs(key) % 2**63).normal())


def budgeted(f):
    """f(cfg) -> fn(cfg, budget) with noise annealing as 1/budget, plus
    a thread-safe cumulative-spend counter."""
    import threading

    lock = threading.Lock()
    spent = [0.0]

    def fn(cfg, budget):
        with lock:
            spent[0] += float(budget)
        return f(cfg) + NOISE_SIGMA * _noise(cfg) / float(budget)

    fn.spent = spent
    return fn


def run_one(name, scheduler, f, make_space, seed):
    """One (domain, scheduler, seed) cell -> (true_best, spent)."""
    from hyperopt_tpu import fmin, tpe_jax
    from hyperopt_tpu.base import Trials
    from hyperopt_tpu.hyperband import (
        asha,
        budget_aware,
        hyperband,
        successive_halving,
    )

    rstate = np.random.default_rng(seed)
    fn = budgeted(f)
    space = make_space()

    if scheduler == "tpe_fmin":
        trials = Trials()
        fmin(
            lambda cfg: fn(cfg, MAX_BUDGET), space,
            algo=tpe_jax.suggest, max_evals=TOTAL // MAX_BUDGET,
            trials=trials, rstate=rstate, show_progressbar=False,
            verbose=False, return_argmin=False,
        )
        best_doc = trials.best_trial
        from hyperopt_tpu.fmin import space_eval

        vals = {
            k: v[0] for k, v in best_doc["misc"]["vals"].items() if v
        }
        best_cfg = space_eval(space, vals)
    elif scheduler == "sha":
        trials = Trials()
        best, best_cfg = np.inf, None
        for _ in range(4):
            out = successive_halving(
                fn, space, max_budget=MAX_BUDGET, eta=ETA,
                n_configs=MAX_BUDGET, trials=trials, rstate=rstate,
            )
            if out["best_loss"] < best:
                best, best_cfg = out["best_loss"], out["best"]
    elif scheduler in ("hyperband", "bohb"):
        algo = budget_aware(tpe_jax.suggest) if scheduler == "bohb" else None
        out = hyperband(
            fn, space, max_budget=MAX_BUDGET, eta=ETA, algo=algo,
            rstate=rstate,
        )
        best_cfg = out["best"]
    elif scheduler.startswith("asha"):
        workers = int(scheduler.split("_")[1][:-1])
        out = asha(
            fn, space, max_budget=MAX_BUDGET, eta=ETA, max_jobs=160,
            workers=workers, rstate=rstate,
        )
        best_cfg = out["best"]
    else:
        raise ValueError(scheduler)
    return float(f(best_cfg)), float(fn.spent[0])


SCHEDULERS = ("tpe_fmin", "sha", "hyperband", "bohb", "asha_4w", "asha_8w")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=5)
    ap.add_argument("--domains", default="surrogate,trap15,nasbench")
    ap.add_argument("--quick", action="store_true",
                    help="1 seed, surrogate only (CI smoke)")
    args = ap.parse_args()
    if os.environ.get("HYPEROPT_TPU_COMPILATION_CACHE", "1") != "0":
        from hyperopt_tpu.utils import enable_compilation_cache

        enable_compilation_cache()

    domains = _domains()
    names = ["surrogate"] if args.quick else args.domains.split(",")
    n_seeds = 1 if args.quick else args.seeds

    results = {}
    for dom in names:
        f, make_space = domains[dom]
        for sched in SCHEDULERS:
            cells = [
                run_one(dom, sched, f, make_space, seed)
                for seed in range(n_seeds)
            ]
            results[f"{dom}/{sched}"] = {
                "median_true_best": round(
                    float(np.median([c[0] for c in cells])), 4
                ),
                "median_spend": round(
                    float(np.median([c[1] for c in cells])), 1
                ),
                "bests": [round(c[0], 4) for c in cells],
            }
            print(json.dumps({f"{dom}/{sched}": results[f"{dom}/{sched}"]}),
                  flush=True)
    print(json.dumps({"battery": results}))


if __name__ == "__main__":
    main()
