"""HPO over actual model training, fused into ONE device program.

Each trial trains its OWN tiny transformer LM (SGD under
``lax.fori_loop``) with the suggested lr/weight-decay; TPE suggest,
all the training, and the history live under one ``lax.scan`` -- zero
host round-trips until the result. On one TPU v5e chip, 512 trials x 8
SGD steps run in ~1 s steady-state (BASELINE.md round 3).

    python examples/08_hpo_over_training.py [--evals 512] [--steps 8]

(``--evals 64 --steps 2`` is the CI smoke configuration.)
"""

import argparse
import os
import time

import numpy as np

from hyperopt_tpu.device_loop import compile_fmin
from hyperopt_tpu.models import transformer


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--evals", type=int, default=512)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=8)
    args = ap.parse_args()
    if os.environ.get("HYPEROPT_TPU_COMPILATION_CACHE", "1") != "0":
        from hyperopt_tpu.utils import enable_compilation_cache

        enable_compilation_cache()

    obj = transformer.device_objective(
        n_steps=args.steps, batch_size=32, seq_len=32, vocab=32,
        d_model=32, n_layers=2,
    )
    runner = compile_fmin(
        obj, transformer.hpo_space(), max_evals=args.evals,
        batch_size=args.batch_size, n_EI_candidates=128,
    )

    t0 = time.perf_counter()
    out = runner(seed=0)  # includes compile
    print(f"compile+run: {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    out = runner(seed=1)  # compiled program is reusable across seeds
    dt = time.perf_counter() - t0
    print(
        f"steady-state: {out['n_evals']} trials x {args.steps} SGD steps "
        f"in {dt:.2f}s\n"
        f"best next-token loss {out['best_loss']:.4f} at "
        f"lr={out['best']['lr']:.4g} wd={out['best']['wd']:.4g} "
        f"(worst evaluated: {np.max(out['losses']):.3f})"
    )


if __name__ == "__main__":
    main()
