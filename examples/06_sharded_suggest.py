"""Mesh-sharded TPE: the candidate sweep split across every device.

`parallel.sharded_suggest` shards the EI candidate sweep over a device
mesh with `shard_map`: each device draws and scores an independent
candidate slab, and the global winner per (trial, dimension) reduces via
an argmax-allgather over the interconnect. Total candidates per dim =
n_EI_per_device x device count.

Works on any `jax.devices()` -- a TPU pod slice, or 8 virtual CPU
devices so the multi-chip program is testable on a laptop:

    HYPEROPT_TPU_VIRTUAL_MESH=1 python examples/06_sharded_suggest.py
    # equivalently:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/06_sharded_suggest.py
"""

import os
import sys

# opt-in virtual mesh; never silently override a real accelerator
if os.environ.get("HYPEROPT_TPU_VIRTUAL_MESH") == "1" and "jax" not in sys.modules:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np


def main():
    import jax

    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.parallel import sharded_suggest

    print("devices:", jax.devices())

    space = {
        "x": hp.uniform("x", -5.0, 5.0),
        "lr": hp.loguniform("lr", np.log(1e-4), np.log(1.0)),
        "layers": hp.choice("layers", [2, 3, 4, 5]),
    }

    def objective(cfg):
        return (
            (cfg["x"] - 1.0) ** 2
            + (np.log(cfg["lr"]) - np.log(3e-3)) ** 2 * 0.1
            + abs(cfg["layers"] - 3) * 0.05
        )

    trials = Trials()
    best = fmin(
        objective,
        space,
        algo=sharded_suggest,  # candidate sweep spans the whole mesh
        max_evals=80,
        trials=trials,
        rstate=np.random.default_rng(0),
        show_progressbar=False,
    )
    print("best:", best)
    print("best loss:", min(trials.losses()))


if __name__ == "__main__":
    main()
