"""Roofline arithmetic for the batched TPE suggest step (VERDICT r2 weak #3).

Publishes the "VPU roofline" claim as checkable numbers instead of a
sentence: exact dominant-term counts derived from the compiled shapes,
sustained on-chip wall-clock per call (completion forced by a scalar
fetch -- ``block_until_ready`` is a no-op on the axon tunnel), and
%-of-peak against an explicitly stated TPU v5e VPU model.

VPU peak model (stated assumption, public numbers):
  - v5e TensorCore: 4 MXUs of 128x128 MACs, bf16 peak 197 TFLOP/s
    => clock ~= 197e12 / (4 * 128*128 * 2) ~= 1.5 GHz.
  - VPU: (8, 128)-lane vector unit with 4 independent ALUs
    => 8*128*4 = 4096 f32 ALU ops/cycle ~= 6.1e12 ALU ops/s at 1.5 GHz.
  - transcendentals (exp, ndtr/erf) run ~1/cycle/lane on the special
    unit; we report %-of-peak under TWO op-cost assumptions: exp/ndtr
    = 1 ALU-equivalent (lower bound) and = 8 (polynomial-expansion
    estimate), bracketing the truth.

Run on the real TPU::

    python examples/roofline.py [--batch 4096] [--n-cand 128] [--profile]

``--profile`` additionally captures a ``jax.profiler`` trace into
``bench_artifacts/roofline_trace`` (works where the tunnel exposes
device tracing; the sustained timing stands alone either way).
"""

import argparse
import json
import time

import numpy as np


def term_counts(ps, cap, batch, n_cand, n_cand_cat, lf_pad):
    """Exact dominant elementwise-term counts for ONE suggest call.

    The inner loops (ops/kernels.py) score every candidate against every
    mixture component: below-model K_b = lf_pad + 1 (prior component),
    above-model K_a = cap + 1.  Continuous non-q dims pay one fused
    mul/exp term per [S, K] cell (gmm_logpdf_cont_pre); quantized dims
    pay two ndtr bin-edge evaluations per cell (gmm_logpdf_quant_pre);
    sampling's one-hot pick + [S,K]x[K,4] contraction and the
    categorical sweep are counted but negligible.
    """
    q_np = np.asarray(ps.q)
    d_nq = int((q_np <= 0).sum())
    d_q = int((q_np > 0).sum())
    k_b = lf_pad + 1
    k_a = cap + 1
    s = n_cand
    per_dim_cells = s * (k_b + k_a)  # ll_below + ll_above grids
    cont_terms = batch * d_nq * per_dim_cells
    quant_terms = batch * d_q * per_dim_cells
    sample_cells = batch * (d_nq + d_q) * s * k_b  # onehot + pick
    cat_cells = int(
        batch * len(ps.cat_idx) * n_cand_cat * max(ps.n_options, default=0)
    )
    return {
        "cont_terms": cont_terms,      # 1 exp + ~6 ALU each
        "quant_terms": quant_terms,    # 2 ndtr + ~4 ALU each
        "sample_cells": sample_cells,  # ~5 ALU each
        "cat_cells": cat_cells,        # ~3 ALU each
    }


def _timed(fn, args_, n_calls, fetch):
    """Sustained per-call seconds with completion forced by a scalar
    fetch (block_until_ready is a no-op on the axon tunnel)."""
    out = fn(*args_)
    _ = np.asarray(fetch(out))
    t0 = time.perf_counter()
    for _i in range(n_calls):
        out = fn(*args_)
    _ = np.asarray(fetch(out))
    return (time.perf_counter() - t0) / n_calls


def run_experiments(args):
    """The three ROOFLINE.md suspects, one experiment each (VERDICT r3
    weak #3).  Prints one JSON line with a win or a measured negative
    per suspect:

    (a) the good/bad-split argsort's share of a suggest call -- timed as
        its own jitted program at the real [cap] shape;
    (b) [S, K] lane alignment -- the above-model grid has K = cap + 1
        components (513 for the 500-obs headline), which XLA pads to
        the next lane multiple (640: ~25% dead lanes); measured by
        scoring at K = 512 vs 513 at equal work;
    (c) bf16 term grids with f32 reduction -- the VPU is a 32-bit-lane
        unit, so the hypothesis is 'no win' (bf16 buys MXU flops and
        HBM bandwidth, not VPU ALU throughput); measured on the
        dominant scoring op standalone.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from hyperopt_tpu import tpe_jax
    from hyperopt_tpu.jax_trials import obs_buffer_for, packed_space_for
    from hyperopt_tpu.models.synthetic import mixed_space
    from hyperopt_tpu.ops import kernels as K

    platform = jax.devices()[0].platform
    domain, trials = bench.build_history(args.n_obs, mixed_space())
    ps = packed_space_for(domain)
    buf = obs_buffer_for(domain, trials)
    arrays = buf.device_arrays()
    cap = int(arrays[2].shape[0])
    B = args.batch
    S = args.n_cand
    n_calls = args.n_calls
    results = {"platform": platform, "batch": B, "n_cand": S, "cap": cap}

    # -- baseline: the full suggest call ---------------------------------
    fn = tpe_jax.build_suggest_fn(ps, S, 0.25, 25.0, 1.0, n_cand_cat=24)
    full_s = _timed(
        lambda: fn(jax.random.key(0), *arrays, batch=B), (), n_calls,
        lambda o: o[0][:1, :1],
    )
    results["full_call_ms"] = round(full_s * 1000, 3)

    # -- (a) argsort share -----------------------------------------------
    split = jax.jit(
        lambda losses, valid: K.split_below_above(losses, valid, 0.25, 25.0)
    )
    split_s = _timed(
        lambda: split(arrays[2], arrays[3]), (), n_calls * 4,
        lambda o: o[2],
    )
    results["split_argsort_ms"] = round(split_s * 1000, 4)
    results["split_share_pct"] = round(100 * split_s / full_s, 2)

    # -- (b) K lane alignment --------------------------------------------
    # the dominant op standalone at the real shapes: [B, D, S] candidates
    # scored against [D, K] component grids, logsumexp over K
    D_nq = 12  # non-quantized continuous dims of the 20-dim space

    def scorer(x, c1, inv_s, mu_inv_s):
        z = x[..., None] * inv_s[None, :, None, :] - mu_inv_s[None, :, None, :]
        terms = c1[None, :, None, :] - 0.5 * z * z
        return jnp.sum(jnp.exp(terms), axis=-1)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (B, D_nq, S)).astype(np.float32))
    for k_width in (cap + 1, cap, cap - 8):
        c1 = jnp.asarray(rng.normal(-1, 0.3, (D_nq, k_width)).astype(np.float32))
        inv_s = jnp.asarray(
            rng.uniform(0.5, 2.0, (D_nq, k_width)).astype(np.float32)
        )
        mu = jnp.asarray(rng.normal(0, 1, (D_nq, k_width)).astype(np.float32))
        f = jax.jit(scorer)
        sec = _timed(
            lambda: f(x, c1, inv_s, mu), (), n_calls, lambda o: o[:1, :1, :1]
        )
        results[f"grid_K{k_width}_ms"] = round(sec * 1000, 3)

    # -- (c) bf16 term grid, f32 reduction -------------------------------
    def scorer_bf16(x, c1, inv_s, mu_inv_s):
        xb = x.astype(jnp.bfloat16)
        z = (
            xb[..., None] * inv_s[None, :, None, :].astype(jnp.bfloat16)
            - mu_inv_s[None, :, None, :].astype(jnp.bfloat16)
        )
        terms = c1[None, :, None, :] - 0.5 * (z * z).astype(jnp.float32)
        return jnp.sum(jnp.exp(terms), axis=-1)

    k_width = cap + 1
    c1 = jnp.asarray(rng.normal(-1, 0.3, (D_nq, k_width)).astype(np.float32))
    inv_s = jnp.asarray(
        rng.uniform(0.5, 2.0, (D_nq, k_width)).astype(np.float32)
    )
    mu = jnp.asarray(rng.normal(0, 1, (D_nq, k_width)).astype(np.float32))
    f16 = jax.jit(scorer_bf16)
    sec16 = _timed(
        lambda: f16(x, c1, inv_s, mu), (), n_calls, lambda o: o[:1, :1, :1]
    )
    results["grid_bf16_ms"] = round(sec16 * 1000, 3)

    print(json.dumps(results))
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--n-cand", type=int, default=128)
    ap.add_argument("--n-obs", type=int, default=500)
    ap.add_argument("--n-calls", type=int, default=30)
    ap.add_argument("--profile", action="store_true")
    ap.add_argument("--experiments", action="store_true",
                    help="run the round-4 roofline-suspect experiments "
                    "instead of the headline arithmetic")
    args = ap.parse_args()
    if args.experiments:
        run_experiments(args)
        return

    import jax

    import bench
    from hyperopt_tpu import tpe_jax
    from hyperopt_tpu.jax_trials import obs_buffer_for, packed_space_for
    from hyperopt_tpu.models.synthetic import mixed_space
    from hyperopt_tpu.ops import kernels as K

    platform = jax.devices()[0].platform
    domain, trials = bench.build_history(args.n_obs, mixed_space())
    ps = packed_space_for(domain)
    buf = obs_buffer_for(domain, trials)
    arrays = buf.device_arrays()
    cap = int(arrays[2].shape[0])
    n_cand_cat = 24
    fn = tpe_jax.build_suggest_fn(
        ps, args.n_cand, 0.25, 25.0, 1.0, n_cand_cat=n_cand_cat
    )
    key = jax.random.key(0)
    out = fn(key, *arrays, batch=args.batch)
    _ = np.asarray(out[0][:1, :1])  # force compile + first run

    keys = list(jax.random.split(key, args.n_calls))
    _ = np.asarray(jax.random.key_data(keys[-1]))
    t0 = time.perf_counter()
    for i in range(args.n_calls):
        out = fn(keys[i], *arrays, batch=args.batch)
    _ = np.asarray(out[0][:1, :1])  # scalar fetch forces completion
    dt = time.perf_counter() - t0
    ms_per_call = dt / args.n_calls * 1000.0

    if args.profile:
        import os

        os.makedirs("bench_artifacts", exist_ok=True)
        try:
            with jax.profiler.trace("bench_artifacts/roofline_trace"):
                for i in range(5):
                    out = fn(keys[i], *arrays, batch=args.batch)
                _ = np.asarray(out[0][:1, :1])
            prof_note = "trace captured in bench_artifacts/roofline_trace"
        except Exception as e:  # tunnel may not expose device tracing
            prof_note = f"profiler unavailable on this attachment: {e!r}"
    else:
        prof_note = "not requested"

    lf_pad = K._below_pad(25.0, cap=cap, gamma=0.25)
    tc = term_counts(ps, cap, args.batch, args.n_cand, n_cand_cat, lf_pad)
    # ALU-op models per cell family (stated in module docstring)
    def total_ops(transcendental_cost):
        return (
            tc["cont_terms"] * (6 + transcendental_cost)
            + tc["quant_terms"] * (4 + 2 * transcendental_cost)
            + tc["sample_cells"] * 5
            + tc["cat_cells"] * 3
        )

    secs = ms_per_call / 1000.0
    terms_per_s = sum(tc.values()) / secs
    vpu_peak = 6.1e12  # 4096 ALU ops/cycle * 1.5 GHz (see docstring)
    lo_ops = total_ops(1) / secs   # exp/ndtr = 1 op (lower bound)
    hi_ops = total_ops(8) / secs   # exp/ndtr = 8 ops (poly estimate)
    print(json.dumps({
        "platform": platform,
        "batch": args.batch,
        "n_cand": args.n_cand,
        "cap": cap,
        "ms_per_call": round(ms_per_call, 2),
        "suggestions_per_sec": round(args.batch / secs, 1),
        "dominant_cells_per_call": tc,
        "gterms_per_sec": round(terms_per_s / 1e9, 1),
        "assumed_vpu_peak_ops_per_sec": vpu_peak,
        "effective_ops_per_sec_low": round(lo_ops / 1e12, 3),
        "effective_ops_per_sec_high": round(hi_ops / 1e12, 3),
        "pct_of_vpu_peak_low": round(100 * lo_ops / vpu_peak, 1),
        "pct_of_vpu_peak_high": round(100 * hi_ops / vpu_peak, 1),
        "profiler": prof_note,
    }))


if __name__ == "__main__":
    main()
