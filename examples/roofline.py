"""Roofline arithmetic for the batched TPE suggest step (VERDICT r2 weak #3).

Publishes the "VPU roofline" claim as checkable numbers instead of a
sentence: exact dominant-term counts derived from the compiled shapes,
sustained on-chip wall-clock per call (completion forced by a scalar
fetch -- ``block_until_ready`` is a no-op on the axon tunnel), and
%-of-peak against an explicitly stated TPU v5e VPU model.

VPU peak model (stated assumption, public numbers):
  - v5e TensorCore: 4 MXUs of 128x128 MACs, bf16 peak 197 TFLOP/s
    => clock ~= 197e12 / (4 * 128*128 * 2) ~= 1.5 GHz.
  - VPU: (8, 128)-lane vector unit with 4 independent ALUs
    => 8*128*4 = 4096 f32 ALU ops/cycle ~= 6.1e12 ALU ops/s at 1.5 GHz.
  - transcendentals (exp, ndtr/erf) run ~1/cycle/lane on the special
    unit; we report %-of-peak under TWO op-cost assumptions: exp/ndtr
    = 1 ALU-equivalent (lower bound) and = 8 (polynomial-expansion
    estimate), bracketing the truth.

Run on the real TPU::

    python examples/roofline.py [--batch 4096] [--n-cand 128] [--profile]

``--profile`` additionally captures a ``jax.profiler`` trace into
``bench_artifacts/roofline_trace`` (works where the tunnel exposes
device tracing; the sustained timing stands alone either way).
"""

import argparse
import json
import time

import numpy as np


def term_counts(ps, cap, batch, n_cand, n_cand_cat, lf_pad):
    """Exact dominant elementwise-term counts for ONE suggest call.

    The inner loops (ops/kernels.py) score every candidate against every
    mixture component: below-model K_b = lf_pad + 1 (prior component),
    above-model K_a = cap + 1.  Continuous non-q dims pay one fused
    mul/exp term per [S, K] cell (gmm_logpdf_cont_pre); quantized dims
    pay two ndtr bin-edge evaluations per cell (gmm_logpdf_quant_pre);
    sampling's one-hot pick + [S,K]x[K,4] contraction and the
    categorical sweep are counted but negligible.
    """
    q_np = np.asarray(ps.q)
    d_nq = int((q_np <= 0).sum())
    d_q = int((q_np > 0).sum())
    k_b = lf_pad + 1
    k_a = cap + 1
    s = n_cand
    per_dim_cells = s * (k_b + k_a)  # ll_below + ll_above grids
    cont_terms = batch * d_nq * per_dim_cells
    quant_terms = batch * d_q * per_dim_cells
    sample_cells = batch * (d_nq + d_q) * s * k_b  # onehot + pick
    cat_cells = int(
        batch * len(ps.cat_idx) * n_cand_cat * max(ps.n_options, default=0)
    )
    return {
        "cont_terms": cont_terms,      # 1 exp + ~6 ALU each
        "quant_terms": quant_terms,    # 2 ndtr + ~4 ALU each
        "sample_cells": sample_cells,  # ~5 ALU each
        "cat_cells": cat_cells,        # ~3 ALU each
    }


def _timed(fn, args_, n_calls, fetch):
    """Sustained per-call seconds with completion forced by a scalar
    fetch (block_until_ready is a no-op on the axon tunnel)."""
    out = fn(*args_)
    _ = np.asarray(fetch(out))
    t0 = time.perf_counter()
    for _i in range(n_calls):
        out = fn(*args_)
    _ = np.asarray(fetch(out))
    return (time.perf_counter() - t0) / n_calls


def run_experiments(args):
    """The three ROOFLINE.md suspects, one experiment each (VERDICT r3
    weak #3).  Prints one JSON line with a win or a measured negative
    per suspect:

    (a) the good/bad-split argsort's share of a suggest call -- timed as
        its own jitted program at the real [cap] shape;
    (b) [S, K] lane alignment -- the above-model grid has K = cap + 1
        components (513 for the 500-obs headline), which XLA pads to
        the next lane multiple (640: ~25% dead lanes); measured by
        scoring at K = 512 vs 513 at equal work;
    (c) bf16 term grids with f32 reduction -- the VPU is a 32-bit-lane
        unit, so the hypothesis is 'no win' (bf16 buys MXU flops and
        HBM bandwidth, not VPU ALU throughput); measured on the
        dominant scoring op standalone.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench
    from hyperopt_tpu import tpe_jax
    from hyperopt_tpu.jax_trials import obs_buffer_for, packed_space_for
    from hyperopt_tpu.models.synthetic import mixed_space
    from hyperopt_tpu.ops import kernels as K

    platform = jax.devices()[0].platform
    domain, trials = bench.build_history(args.n_obs, mixed_space())
    ps = packed_space_for(domain)
    buf = obs_buffer_for(domain, trials)
    arrays = buf.device_arrays()
    cap = int(arrays[2].shape[0])
    B = args.batch
    S = args.n_cand
    n_calls = args.n_calls
    results = {"platform": platform, "batch": B, "n_cand": S, "cap": cap}

    # -- baseline: the full suggest call ---------------------------------
    fn = tpe_jax.build_suggest_fn(ps, S, 0.25, 25.0, 1.0, n_cand_cat=24)
    full_s = _timed(
        lambda: fn(jax.random.key(0), *arrays, batch=B), (), n_calls,
        lambda o: o[0][:1, :1],
    )
    results["full_call_ms"] = round(full_s * 1000, 3)

    # -- (a) argsort share -----------------------------------------------
    split = jax.jit(
        lambda losses, valid: K.split_below_above(losses, valid, 0.25, 25.0)
    )
    split_s = _timed(
        lambda: split(arrays[2], arrays[3]), (), n_calls * 4,
        lambda o: o[2],
    )
    results["split_argsort_ms"] = round(split_s * 1000, 4)
    results["split_share_pct"] = round(100 * split_s / full_s, 2)

    # -- (b) K lane alignment --------------------------------------------
    # the dominant op standalone at the real shapes: [B, D, S] candidates
    # scored against [D, K] component grids, logsumexp over K
    D_nq = 12  # non-quantized continuous dims of the 20-dim space

    def scorer(x, c1, inv_s, mu_inv_s):
        z = x[..., None] * inv_s[None, :, None, :] - mu_inv_s[None, :, None, :]
        terms = c1[None, :, None, :] - 0.5 * z * z
        return jnp.sum(jnp.exp(terms), axis=-1)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (B, D_nq, S)).astype(np.float32))
    # one jitted callable for all widths (GL104): each k_width still
    # traces its own shape, but through one program cache
    f = jax.jit(scorer)
    for k_width in (cap + 1, cap, cap - 8):
        c1 = jnp.asarray(rng.normal(-1, 0.3, (D_nq, k_width)).astype(np.float32))
        inv_s = jnp.asarray(
            rng.uniform(0.5, 2.0, (D_nq, k_width)).astype(np.float32)
        )
        mu = jnp.asarray(rng.normal(0, 1, (D_nq, k_width)).astype(np.float32))
        sec = _timed(
            lambda: f(x, c1, inv_s, mu), (), n_calls, lambda o: o[:1, :1, :1]
        )
        results[f"grid_K{k_width}_ms"] = round(sec * 1000, 3)

    # -- (c) bf16 term grid, f32 reduction -------------------------------
    def scorer_bf16(x, c1, inv_s, mu_inv_s):
        xb = x.astype(jnp.bfloat16)
        z = (
            xb[..., None] * inv_s[None, :, None, :].astype(jnp.bfloat16)
            - mu_inv_s[None, :, None, :].astype(jnp.bfloat16)
        )
        terms = c1[None, :, None, :] - 0.5 * (z * z).astype(jnp.float32)
        return jnp.sum(jnp.exp(terms), axis=-1)

    k_width = cap + 1
    c1 = jnp.asarray(rng.normal(-1, 0.3, (D_nq, k_width)).astype(np.float32))
    inv_s = jnp.asarray(
        rng.uniform(0.5, 2.0, (D_nq, k_width)).astype(np.float32)
    )
    mu = jnp.asarray(rng.normal(0, 1, (D_nq, k_width)).astype(np.float32))
    f16 = jax.jit(scorer_bf16)
    sec16 = _timed(
        lambda: f16(x, c1, inv_s, mu), (), n_calls, lambda o: o[:1, :1, :1]
    )
    results["grid_bf16_ms"] = round(sec16 * 1000, 3)

    print(json.dumps(results))
    return results


def run_b1(args):
    """Per-trial cost decomposition of the SEQUENTIAL B=1 device loop --
    the flagship quality mode (VERDICT r4 weak #1).

    The batched roofline says nothing about this regime: at B=1 the
    [S, K] sweep is ~4096x smaller than the benched B=4096 program, so
    fixed per-step costs dominate.  Each component of the step
    (``device_loop.compile_fmin`` batch_size=1) is timed as its own
    1000-iteration ``lax.scan`` at the REAL shapes (cap=1024 history,
    20-dim mixed space, 128/24 candidates), output folded into a scalar
    carry (serializes steps + defeats DCE), completion forced by the
    scalar fetch.  Prints one JSON line with ms/step per component.
    """
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.device_loop import compile_fmin
    from hyperopt_tpu.models.synthetic import mixed_space, mixed_space_fn_jax
    from hyperopt_tpu.ops import kernels as K
    from hyperopt_tpu.ops.compile import compile_space

    platform = jax.devices()[0].platform
    N = args.b1_steps  # steps per component program
    S, S_cat = args.n_cand, 24
    gamma, lf, pw = 0.25, 25.0, 1.0
    results = {"platform": platform, "n_steps": N, "n_cand": S}

    # -- the real thing: full runner, tpe vs rand ------------------------
    space = mixed_space()
    for algo in ("tpe", "rand"):
        runner = compile_fmin(
            mixed_space_fn_jax, space, max_evals=N, batch_size=1,
            n_EI_candidates=S, n_EI_candidates_cat=S_cat, algo=algo,
        )
        runner(seed=1)  # compile (runner fetches its results = completion)
        t0 = time.perf_counter()
        runner(seed=7)
        results[f"loop_{algo}_ms"] = round(
            (time.perf_counter() - t0) / N * 1000, 4
        )

    # -- components, each as its own scan at the real shapes -------------
    ps = compile_space(space)
    c = ps._consts
    cap = 1024
    key0 = jax.random.key(0)
    values, active = jax.device_get(ps.sample_prior(key0, cap))
    values = jnp.asarray(values)
    active = jnp.asarray(active)
    losses = jnp.asarray(
        np.random.default_rng(0).uniform(0, 10, cap).astype(np.float32)
    )
    valid = jnp.ones((cap,), bool)
    cont_idx = c["cont_idx"]
    lat = jnp.where(
        c["logspace"][:, None], jnp.log(jnp.maximum(values[cont_idx], 1e-30)),
        values[cont_idx],
    )
    act_c = active[cont_idx]
    dc = int(cont_idx.shape[0])
    pw_v = jnp.full((dc,), pw, jnp.float32)
    lf_v = jnp.full((dc,), lf, jnp.float32)
    lf_pad = K._below_pad(lf, cap=cap, gamma=gamma)
    below0, above0, _ = K.split_below_above(losses, valid, gamma, lf)
    fits0 = K.fit_all_dims(c, values, active, losses, valid, gamma, lf, pw)

    def timed_scan(name, step_fn):
        @jax.jit
        def prog(key):
            def body(acc, i):
                return acc + step_fn(jax.random.fold_in(key, i)), None
            acc, _ = jax.lax.scan(body, jnp.float32(0.0), jnp.arange(N))
            return acc
        float(prog(jax.random.key(1)))  # compile + first run
        t0 = time.perf_counter()
        float(prog(jax.random.key(2)))  # scalar fetch forces completion
        results[name] = round((time.perf_counter() - t0) / N * 1000, 4)

    # scan floor: key fold + a trivial draw
    timed_scan("scan_floor_ms", lambda k: jax.random.uniform(k, ()))

    # good/bad split: argsort [cap] + rank scatter
    def step_split(k):
        b, a, nb = K.split_below_above(
            losses + jax.random.uniform(k, ()), valid, gamma, lf
        )
        return jnp.sum(b.astype(jnp.float32)) + nb

    timed_scan("split_ms", step_split)

    # below-set compaction: vmapped stable argsort [cap] per cont dim
    def step_compact(k):
        m = act_c & (below0[None, :] ^ (jax.random.uniform(k, ()) > 2.0))
        lat_b, mask_b = jax.vmap(K.compact_below, in_axes=(0, 0, None))(
            lat, m, lf_pad
        )
        return jnp.sum(lat_b * mask_b)

    timed_scan("compact_below_ms", step_compact)

    # above-model Parzen fit: vmapped argsort-by-mu at [cap + 1]
    def step_fit_above(k):
        wa, ma, sa = jax.vmap(K.parzen_fit)(
            lat + jax.random.uniform(k, ()), act_c & above0[None, :],
            c["prior_mu"], c["prior_sigma"], pw_v, lf_v,
        )
        return jnp.sum(wa) + jnp.sum(ma[:, :2]) + jnp.sum(sa[:, :2])

    timed_scan("fit_above_cont_ms", step_fit_above)

    # the whole fit front half (split + compact + below/above + cat)
    def step_fit_all(k):
        f = K.fit_all_dims(
            c, values, active, losses + jax.random.uniform(k, ()),
            valid, gamma, lf, pw,
        )
        out = jnp.float32(0.0)
        for fam in ("cont", "cat"):
            if f[fam] is not None:
                out += sum(jnp.sum(t[:, :2]) for t in f[fam])
        return out

    timed_scan("fit_all_ms", step_fit_all)

    # EI candidate sweep at B=1 with FIXED fits (the back half)
    def step_sweep(k):
        keys = jax.random.split(k, ps.n_dims)
        v_cont, s_cont = K.ei_sweep_cont(
            ps.q, c, keys[None, :dc], fits0["cont"], S
        )
        v_cat, s_cat = K.ei_sweep_cat(
            keys[None, dc:], *fits0["cat"], S_cat
        )
        return jnp.sum(v_cont) + jnp.sum(s_cont) + jnp.sum(v_cat)

    timed_scan("sweep_ms", step_sweep)

    # objective eval + history scatter (buffer carry, fixed suggestion)
    col = values[:, :1]
    acol = active[:, :1]

    @jax.jit
    def prog_scatter(key):
        def body(carry, i):
            v, a, l = carry
            # fold i: a loop-invariant objective would be hoisted out of
            # the scan and the component would time only the scatter
            ki = jax.random.fold_in(key, i)
            nl = mixed_space_fn_jax(
                {lab: col[d] + jax.random.uniform(ki, ())
                 for d, lab in enumerate(ps.labels)}
            )
            idx = i * 1 + jnp.arange(1)
            return (
                v.at[:, idx].set(col), a.at[:, idx].set(acol),
                l.at[idx].set(nl.astype(jnp.float32)),
            ), None
        (v, a, l), _ = jax.lax.scan(
            body, (values, active, losses), jnp.arange(N)
        )
        return jnp.sum(l[:4])

    float(prog_scatter(jax.random.key(1)))
    t0 = time.perf_counter()
    float(prog_scatter(jax.random.key(2)))
    results["eval_scatter_ms"] = round((time.perf_counter() - t0) / N * 1000, 4)

    print(json.dumps(results))
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--n-cand", type=int, default=128)
    ap.add_argument("--n-obs", type=int, default=500)
    ap.add_argument("--n-calls", type=int, default=30)
    ap.add_argument("--profile", action="store_true")
    ap.add_argument("--experiments", action="store_true",
                    help="run the round-4 roofline-suspect experiments "
                    "instead of the headline arithmetic")
    ap.add_argument("--b1", action="store_true",
                    help="decompose the sequential B=1 device loop's "
                    "per-trial cost (round-5)")
    ap.add_argument("--b1-steps", type=int, default=1000,
                    help="steps per component program in --b1 mode")
    args = ap.parse_args()
    if args.experiments:
        run_experiments(args)
        return
    if args.b1:
        run_b1(args)
        return

    import jax

    import bench
    from hyperopt_tpu import tpe_jax
    from hyperopt_tpu.jax_trials import obs_buffer_for, packed_space_for
    from hyperopt_tpu.models.synthetic import mixed_space
    from hyperopt_tpu.ops import kernels as K

    platform = jax.devices()[0].platform
    domain, trials = bench.build_history(args.n_obs, mixed_space())
    ps = packed_space_for(domain)
    buf = obs_buffer_for(domain, trials)
    arrays = buf.device_arrays()
    cap = int(arrays[2].shape[0])
    n_cand_cat = 24
    fn = tpe_jax.build_suggest_fn(
        ps, args.n_cand, 0.25, 25.0, 1.0, n_cand_cat=n_cand_cat
    )
    key = jax.random.key(0)
    out = fn(key, *arrays, batch=args.batch)
    _ = np.asarray(out[0][:1, :1])  # force compile + first run

    keys = list(jax.random.split(key, args.n_calls))
    _ = np.asarray(jax.random.key_data(keys[-1]))
    t0 = time.perf_counter()
    for i in range(args.n_calls):
        out = fn(keys[i], *arrays, batch=args.batch)
    _ = np.asarray(out[0][:1, :1])  # scalar fetch forces completion
    dt = time.perf_counter() - t0
    ms_per_call = dt / args.n_calls * 1000.0

    if args.profile:
        import os

        os.makedirs("bench_artifacts", exist_ok=True)
        try:
            with jax.profiler.trace("bench_artifacts/roofline_trace"):
                for i in range(5):
                    out = fn(keys[i], *arrays, batch=args.batch)
                _ = np.asarray(out[0][:1, :1])
            prof_note = "trace captured in bench_artifacts/roofline_trace"
        except Exception as e:  # tunnel may not expose device tracing
            prof_note = f"profiler unavailable on this attachment: {e!r}"
    else:
        prof_note = "not requested"

    lf_pad = K._below_pad(25.0, cap=cap, gamma=0.25)
    tc = term_counts(ps, cap, args.batch, args.n_cand, n_cand_cat, lf_pad)
    # ALU-op models per cell family (stated in module docstring)
    def total_ops(transcendental_cost):
        return (
            tc["cont_terms"] * (6 + transcendental_cost)
            + tc["quant_terms"] * (4 + 2 * transcendental_cost)
            + tc["sample_cells"] * 5
            + tc["cat_cells"] * 3
        )

    secs = ms_per_call / 1000.0
    terms_per_s = sum(tc.values()) / secs
    vpu_peak = 6.1e12  # 4096 ALU ops/cycle * 1.5 GHz (see docstring)
    lo_ops = total_ops(1) / secs   # exp/ndtr = 1 op (lower bound)
    hi_ops = total_ops(8) / secs   # exp/ndtr = 8 ops (poly estimate)
    print(json.dumps({
        "platform": platform,
        "batch": args.batch,
        "n_cand": args.n_cand,
        "cap": cap,
        "ms_per_call": round(ms_per_call, 2),
        "suggestions_per_sec": round(args.batch / secs, 1),
        "dominant_cells_per_call": tc,
        "gterms_per_sec": round(terms_per_s / 1e9, 1),
        "assumed_vpu_peak_ops_per_sec": vpu_peak,
        "effective_ops_per_sec_low": round(lo_ops / 1e12, 3),
        "effective_ops_per_sec_high": round(hi_ops / 1e12, 3),
        "pct_of_vpu_peak_low": round(100 * lo_ops / vpu_peak, 1),
        "pct_of_vpu_peak_high": round(100 * hi_ops / vpu_peak, 1),
        "profiler": prof_note,
    }))


if __name__ == "__main__":
    main()
