"""Conditional (tree-structured) spaces: hp.choice subtrees.

A trial only carries values for the hyperparameters on its active
branch -- the sparse idxs/vals encoding of the reference, reproduced by
the compiled dense+mask sampler.

    python examples/02_conditional_space.py
"""

import numpy as np

from hyperopt_tpu import Trials, fmin, hp, tpe_jax

space = hp.choice(
    "model",
    [
        {
            "type": "mlp",
            "depth": hp.randint("mlp_depth", 2, 8),
            "width": hp.qloguniform("mlp_width", np.log(32), np.log(1024), 32),
        },
        {
            "type": "cnn",
            "blocks": hp.randint("cnn_blocks", 1, 5),
            "channels": hp.quniform("cnn_channels", 16, 128, 16),
        },
    ],
)


def objective(cfg):
    if cfg["type"] == "mlp":
        return abs(cfg["depth"] - 4) * 0.2 + abs(cfg["width"] - 256) / 1024
    return abs(cfg["blocks"] - 3) * 0.15 + abs(cfg["channels"] - 64) / 256


def main():
    trials = Trials()
    fmin(
        objective, space, algo=tpe_jax.suggest, max_evals=120, trials=trials,
        rstate=np.random.default_rng(0), show_progressbar=False,
    )
    best = trials.best_trial
    print("best loss:", best["result"]["loss"])
    print("best vals (sparse; inactive branch empty):")
    for label, vals in sorted(best["misc"]["vals"].items()):
        print(f"  {label}: {vals}")


if __name__ == "__main__":
    main()
