"""Budget-aware schedulers the reference cannot express, on-device.

Ways to spend a training budget smarter than independent trials:

* **PBT** (``pbt.compile_pbt``): the population trains as one program;
  every ``exploit_every`` steps the bottom quartile copies a top
  member's weights and perturbs its hyperparameters.  The result dict
  RESUMES (``runner(init=prev_out)``) -- checkpoint/continue mid-study.
* **Successive halving** (``hyperband.compile_sha``): rungs of
  shrinking population and growing budget; survivors CONTINUE from
  their trained state.  ``replicas=K`` packs K independent brackets
  into every rung program (late rungs fill the chip with other
  brackets' members -- K results for ~one bracket's wall-clock).
* **Hyperband** (``hyperband.compile_hyperband``): the full bracket
  spread as chained ladders.

All share the same train-fn contract and run here over a tiny
transformer LM population (models/transformer.py).

    python examples/09_pbt_and_sha.py [--pop 16] [--rounds 10]

(``--pop 4 --rounds 2`` is the CI smoke configuration.)
"""

import argparse

import numpy as np

import jax
import jax.numpy as jnp

from hyperopt_tpu.hyperband import compile_hyperband, compile_sha
from hyperopt_tpu.models import transformer
from hyperopt_tpu.pbt import compile_pbt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pop", type=int, default=16)
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--no-compilation-cache", action="store_true",
                    help="skip the persistent XLA compilation cache "
                    "(on by default: the scheduler ladders are the "
                    "most compile-heavy programs in the framework)")
    args = ap.parse_args()

    if not args.no_compilation_cache:
        from hyperopt_tpu.utils import enable_compilation_cache

        enable_compilation_cache()

    P = args.pop
    model = transformer.TinyLM(vocab=32, d_model=32, n_heads=2, n_layers=2,
                               max_len=32)
    params = transformer.init_population(
        model, P, jax.random.key(0), seq_len=32
    )
    momentum = jax.tree.map(jnp.zeros_like, params)
    train_fn = transformer.make_pbt_train_fn(
        model, batch_size=32, seq_len=32, vocab=32
    )
    bounds = {"lr": (1e-4, 1.0), "wd": (1e-7, 1e-2)}

    pbt_runner = compile_pbt(
        train_fn, (params, momentum), bounds,
        pop_size=P, exploit_every=5, n_rounds=args.rounds,
    )
    out = pbt_runner(seed=0)
    print(
        f"PBT: {P} members x {out['n_steps']} steps -> "
        f"best {out['best_loss']:.4f}, population median "
        f"{np.nanmedian(out['loss_history'][-1]):.4f} "
        f"(best lr {out['best_hypers']['lr']:.3g})"
    )
    resumed = pbt_runner(seed=1, init=out)  # checkpoint/continue
    print(
        f"PBT resumed +{resumed['n_steps']} steps -> "
        f"best {resumed['best_loss']:.4f}"
    )

    sha_runner = compile_sha(
        train_fn, (params, momentum), bounds,
        n_configs=P, eta=2, steps_per_rung=5,
    )
    out = sha_runner(seed=0)
    sched = " -> ".join(f"{r['n']}x{r['steps']}" for r in out["rungs"])
    print(
        f"SHA: rungs {sched} (survivors continue training) -> "
        f"best {out['best_loss']:.4f} (lr {out['best_hypers']['lr']:.3g})"
    )

    def init_members(key, n):
        p = transformer.init_population(model, n, key, seq_len=32)
        return (p, jax.tree.map(jnp.zeros_like, p))

    hb_runner = compile_hyperband(
        train_fn, init_members, bounds, s_max=2, eta=2, steps_per_rung=3,
    )
    out = hb_runner(seed=0)
    print(
        f"Hyperband: brackets "
        f"{[b['n_configs'] for b in out['brackets']]} -> "
        f"best {out['best_loss']:.4f} (bracket {out['best_bracket']})"
    )


if __name__ == "__main__":
    main()
