"""Low-latency sequential tuning with speculative batching.

A sequential fmin asks for ONE suggestion, evaluates it, and repeats --
the reference's default workflow.  On a remote-attached TPU every ask
pays a synchronous dispatch round-trip (~100 ms over a tunnel; see
BASELINE.md's dispatch/compute decomposition).  ``speculative=k`` keeps
the per-trial API but draws k suggestions under one dispatch and serves
the next k-1 asks from cache while the posterior is at most ``k-1``
completed observations stale -- the same staleness the reference's
``fmin(max_queue_len=k)`` accepts, at one dispatch per k trials.

Avoid on small pure-categorical spaces (the saturated EI argmax makes
the k columns near-duplicates; BASELINE.md has the measurement).

    python examples/07_speculative_sequential.py
"""

import time
from functools import partial

import numpy as np

from hyperopt_tpu import Trials, fmin, hp, tpe_jax
from hyperopt_tpu.jax_trials import JaxTrials


def objective(cfg):
    # continuous/mixed space: the regime speculative batching is for
    return (
        (cfg["x"] - 1.0) ** 2 / 10.0
        + (np.log(cfg["lr"]) + 6.0) ** 2 / 20.0
        + abs(cfg["width"] - 48) / 100.0
    )


space = {
    "x": hp.uniform("x", -5.0, 5.0),
    "lr": hp.loguniform("lr", np.log(1e-5), np.log(1e-1)),
    "width": hp.quniform("width", 8, 128, 8),
}


def run(algo, label, n=120):
    trials = JaxTrials()
    t0 = time.perf_counter()
    fmin(
        objective, space, algo=algo, max_evals=n, trials=trials,
        rstate=np.random.default_rng(0), show_progressbar=False,
        return_argmin=False,
    )
    dt = time.perf_counter() - t0
    print(
        f"{label:24s} {n} sequential trials in {dt:6.2f}s "
        f"({n / dt:7.1f} trials/s), best loss {min(trials.losses()):.5f}"
    )


def main():
    run(tpe_jax.suggest, "plain per-trial asks")
    run(partial(tpe_jax.suggest, speculative=8), "speculative=8")
    print("done")


if __name__ == "__main__":
    main()
